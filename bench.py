"""Benchmarks for the BASELINE.json configs plus the scale/serving tiers.

Prints ONE JSON line per config, headline first:

1. als_ml100k_train_wall_clock — "scala-parallel-recommendation ALS
   (MovieLens-100K, rank=10)" at exact ML-100K shape (943 x 1682, 100k
   ratings; the real dataset is not redistributable in this image, so
   ratings are synthesized with a low-rank-plus-noise model at the same
   shape/sparsity/margins). Extra fields:
     rmse_train           fit sanity (< 1.0 at parity quality)
     rmse_vs_mllib        |RMSE(TPU kernel) - RMSE(numpy oracle of MLlib
                          1.3 ALS-WR semantics, ops/als_reference.py)| on
                          identical data — the north-star parity evidence
     predict_device_compute_ms  amortized per-call device time of the
                          serving op (chained on-device loop; cancels the
                          relay round trip that even block_until_ready pays)
     predict_p50_ms       p50 including the device->host result fetch
     relay_rtt_p50_ms     the bare dispatch+fetch round trip this rig
                          charges ANY result-returning call (measured
                          interleaved with the predict loop)
     predict_p50_ms_minus_rtt  true device+host serving cost beyond the
                          single documented round trip (<10 ms north star)
     rest_p50_ms/p99/qps  end-to-end POST /queries.json through the
                          EngineServer micro-batching executor under 32
                          concurrent clients (includes the relay fetch)
     predict_inproc_p50_ms/p99/qps  the same serving core measured
                          IN-PROCESS against QueryAPI.handle — no
                          sockets, no HTTP parse — the direct serving
                          latency that the RTT-subtraction estimate
                          above only approximates
2. nb_classification_train_wall_clock — NaiveBayes over user properties.
3. similarproduct_train_wall_clock — implicit ALS + cosine top-N.
4. ecommerce_train_wall_clock — explicit ALS + predict-time rules.
5. kfold_cv_eval_wall_clock — MetricEvaluator grid (2 ranks x 2 regs,
   3 folds) through CoreWorkflow.run_evaluation.
6. als_ml20m_train_wall_clock — north-star scale (138k x 27k x 20M,
   rank 32), phase-split with a measured memory-bound roofline (see
   bench_ml20m).
7. als_ml20m_store_to_model_wall_clock — the flagship flow THROUGH the
   event store, via the STREAMING pipeline (ops/streaming): chunked
   scan || pack fold -> counting-sort merge -> double-buffered
   device_put, compile hidden under scan+pack. Cold (pack-cache miss)
   and warm (fingerprint hit: scan+pack skipped) trains both run;
   train_pack_exposed_s / train_device_put_exposed_s are the
   critical-path remainders, and rmse_vs_mllib checks BOTH cache paths
   against the float64 oracle on a parity sub-app.
8. eventserver_ingest_events_per_sec — Event Server write-path
   throughput under concurrent clients. The headline posts batches
   through the reference-parity /batch/events.json route (each request
   one group-commit unit, <= 50 events); single-event POST /events.json
   throughput rides along as single_event_events_per_sec. The
   concurrent_ingest config runs the same harness against a
   hash-SHARDED sqlite store (SHARDS=4, per-shard group committers)
   with a training scan looping in flight.

vs_baseline divides a conservative Spark-1.3-local wall-clock estimate for
the same config by the measured time (the reference publishes no numbers,
BASELINE.md; estimates are labeled in each section).
"""

import concurrent.futures
import json
import os
import sys
import time

import numpy as np

N_USERS, N_ITEMS, N_RATINGS = 943, 1682, 100_000
RANK, ITERS = 10, 10

# Conservative Spark 1.3 local[*] wall-clock estimates for each config
# (the reference publishes no numbers; these are deliberately low-end so
# vs_baseline understates rather than overstates the speedup).
SPARK_LOCAL_ALS_S = 30.0  # MLlib ALS ML-100K rank=10 iters=10
SPARK_LOCAL_NB_S = 8.0  # MLlib NaiveBayes, ~50k points
SPARK_LOCAL_SIMILAR_S = 30.0  # trainImplicit + item-factor cosine
SPARK_LOCAL_ECOMM_S = 30.0  # ALS.train + LEventStore rule reads
SPARK_LOCAL_CV_S = 240.0  # 4 variants x 3 folds, each an ALS train+eval
SPARK_LOCAL_ALS_ML20M_S = 900.0  # MLlib ALS ML-20M rank=32 iters=10 local[*]

# Published per-chip peak dense-matmul rates (bf16), for the MFU field of
# the ML-20M bench. Keyed by jax device_kind; unknown kinds report mfu=None
# rather than a number derived from a guessed peak.
PEAK_BF16_FLOPS = {
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5e": 197e12,
    "TPU v4": 275e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,  # v6e / Trillium
    "TPU v6e": 918e12,
}

# Published per-chip HBM bandwidth, the denominator of the device-loop
# roofline analysis (ALS is memory-bound, not FLOP-bound — see
# bench_ml20m).
PEAK_HBM_GBPS = {
    "TPU v5 lite": 819,  # v5e
    "TPU v5e": 819,
    "TPU v4": 1228,
    "TPU v5p": 2765,
    "TPU v6 lite": 1640,
    "TPU v6e": 1640,
}


def measure_gather_ceiling_mrows(n_rows=26_744, k=32, m=4_194_304, iters=16):
    """Measured per-chip ceiling of the op that fundamentally bounds ALS
    on TPU: an [m]-index row gather from an [n_rows, k] factor table.
    TPU has no hardware gather — XLA lowers it to a row-rate-bound loop
    (~420 Mrows/s on v5e regardless of row dtype), far below HBM byte
    peak. The device loop's gather phase should be judged against THIS
    roofline, not the HBM number. Chained on-device iterations cancel
    the relay round trip."""
    import jax
    import jax.numpy as jnp

    idx = jax.device_put(
        np.random.default_rng(0).integers(0, n_rows, m).astype(np.int32)
    )
    table = jax.device_put(np.ones((n_rows, k), np.float32))

    @jax.jit
    def chain(idx, table, n):
        def body(j, acc):
            t = table * (1.0 + acc * 1e-30)
            return acc + jnp.sum(t[idx].astype(jnp.float32)) * 1e-30
        return jax.lax.fori_loop(0, n, body, 0.0)

    jax.device_get(chain(idx, table, jnp.int32(1)))
    t0 = time.perf_counter()
    jax.device_get(chain(idx, table, jnp.int32(1)))
    t1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    jax.device_get(chain(idx, table, jnp.int32(iters)))
    tk = time.perf_counter() - t0
    per = max((tk - t1) / (iters - 1), 1e-9)
    return m / per / 1e6


def synth_ml100k(seed=7):
    rng = np.random.default_rng(seed)
    k = 6
    U = rng.standard_normal((N_USERS, k)) / np.sqrt(k)
    V = rng.standard_normal((N_ITEMS, k)) / np.sqrt(k)
    # ML-100K-like long-tail: user activity ~ lognormal, item popularity zipf
    u_p = rng.lognormal(0, 1, N_USERS)
    u_p /= u_p.sum()
    i_p = 1.0 / np.arange(1, N_ITEMS + 1) ** 0.8
    i_p /= i_p.sum()
    u = rng.choice(N_USERS, size=N_RATINGS, p=u_p).astype(np.int32)
    i = rng.choice(N_ITEMS, size=N_RATINGS, p=i_p).astype(np.int32)
    raw = (U[u] * V[i]).sum(-1)
    r = np.clip(np.round(3.0 + 1.2 * raw + 0.4 * rng.standard_normal(N_RATINGS)), 1, 5)
    return u, i, r.astype(np.float32)


EMITTED = []  # every record this run, for the tail summary line


def emit(payload, baseline_s=None):
    """Print one JSON record. ``baseline_s`` is the synthetic Spark-local
    estimate behind vs_baseline (the reference publishes no numbers,
    BASELINE.md) — recorded as ``baseline_s`` + ``baseline_estimated`` so
    the JSON is self-describing about the denominator's provenance."""
    if baseline_s is not None and "vs_baseline" in payload:
        payload = {
            **payload,
            "baseline_s": baseline_s,
            "baseline_estimated": True,
        }
    EMITTED.append(payload)
    print(json.dumps(payload), flush=True)


# Headline fields repeated in the final summary line, keyed by metric.
# The driver captures the TAIL of bench output; the headline serving
# block is emitted FIRST, so without this repeat a truncated capture
# loses exactly the north-star numbers (round-4 verdict missing #4).
_SUMMARY_FIELDS = {
    "als_ml100k_train_wall_clock": (
        "value", "rmse_vs_mllib", "predict_p50_ms", "relay_rtt_p50_ms",
        "predict_p50_ms_minus_rtt", "predict_device_compute_ms",
        "predict_inproc_p50_ms", "rest_p50_ms", "rest_qps",
        "batch_fill_mean", "rest_single_client_p50_ms",
        "healthz_p50_ms",
    ),
    "eventserver_ingest_events_per_sec": (
        "value", "single_event_events_per_sec",
    ),
    "concurrent_ingest_events_per_sec": ("value", "shards"),
    "segment_scan_events_per_sec": (
        "value", "row_scan_events_per_sec", "speedup_vs_row_store",
    ),
    "als_ml20m_train_wall_clock": (
        "value", "device_loop_s", "loop_vs_roofline", "device_put_s",
        "wire_mb", "convergence",
    ),
    "als_ml20m_store_to_model_wall_clock": (
        "value", "train_s", "store_scan_s", "train_pack_exposed_s",
        "train_device_put_exposed_s", "pack_cache_warm", "warm_train_s",
        "rmse_vs_mllib",
    ),
    "delta_retrain_s": (
        "value", "cold_retrain_s", "delta_over_cold", "delta_rmse_gap",
        "delta_events", "delta_convergence", "cold_convergence",
        "sweep_telemetry_overhead_frac",
    ),
    "implicit_train_s": (
        "value", "exact_loop_s", "solve_speedup", "hit_rate_exact",
        "hit_rate_subspace", "oracle_rmse_gap", "upload_over_encoded",
    ),
    "retrieval_qps": (
        "value", "retrieval_p99_ms", "retrieval_vs_naive_speedup",
        "workers", "errors", "retrieval_parity", "catalog_items",
    ),
    "promotion_under_load": (
        "value", "p99_baseline_ms", "swap_window_s", "qps_under_load",
        "errors", "shadow_refusal_enforced", "rollback_on_regression",
    ),
    "experiment_plane": (
        "value", "winner_promoted", "aa_no_winner",
        "cross_variant_reassignments", "errors", "loser_ledger_zero",
        "attribution_overhead_frac",
    ),
    "cluster_ingest": (
        "value", "events_per_sec_1node", "scaling_4_over_1", "cores",
        "acked_events_lost", "wire_identical_node_down",
        "wire_identical_recovered", "model_fingerprint_unchanged",
        "resynced_events",
    ),
    "collector_fleet": (
        "value", "qps_no_collector", "scrape_overhead_frac",
        "stitched_processes", "federation_exact", "collector_targets",
        "errors",
    ),
    "device_obs": (
        "value", "serving_p50_ms", "instr_ms_per_batch",
        "profile_archive_bytes", "errors_during_capture",
        "ledger_resident_mb", "ledger_bytes_after_release",
    ),
}


def emit_summary():
    """One compact tail record repeating the headline metrics of every
    config that ran, so tail-truncated captures keep them."""
    summary = {"metric": "summary", "unit": "mixed"}
    for rec in EMITTED:
        fields = _SUMMARY_FIELDS.get(rec.get("metric"))
        if not fields:
            continue
        short = rec["metric"].replace("_wall_clock", "")
        for f in fields:
            if rec.get(f) is not None:
                key = f"{short}.{f}" if f != "value" else short
                summary[key] = rec[f]
    print(json.dumps(summary), flush=True)


def pctl(xs, q):
    return float(np.percentile(np.asarray(xs), q))


# --- /metrics scraping (observability round): bench windows are
# bracketed by a scrape of the server's own /metrics so the bench JSON
# carries the COUNTER evidence (batch fill, flush sizes) instead of log
# prose — the same families an operator's Prometheus would collect ---


def scrape_metrics(port: int) -> dict:
    """GET /metrics on localhost:port, parsed to {'name{labels}': value}."""
    import http.client

    from predictionio_tpu.utils.metrics import parse_exposition

    conn = http.client.HTTPConnection("localhost", port, timeout=10)
    try:
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        body = resp.read().decode("utf-8")
        assert resp.status == 200, resp.status
        return parse_exposition(body)
    finally:
        conn.close()


def metrics_delta(before: dict, after: dict, prefixes) -> dict:
    """after-minus-before for every sample whose name starts with one of
    ``prefixes``. Per-bucket lines are dropped — the summary evidence is
    the _sum/_count pairs (mean fill = sum/count) and plain counters;
    anyone who wants the full bucket vectors scrapes /metrics."""
    out = {}
    for key, val in after.items():
        if not any(key.startswith(p) for p in prefixes):
            continue
        if "_bucket{" in key or key.endswith("_bucket"):
            continue
        d = val - before.get(key, 0.0)
        if d:
            out[key] = round(d, 6)
    return out


def measure_metrics_overhead_us(n: int = 20000) -> float:
    """Per-request registry cost on the serving path (one histogram
    observe + one counter inc + one gauge set), in microseconds — the
    in-proc regression gate for the instrumentation itself."""
    from predictionio_tpu.utils import metrics as _m

    reg = _m.MetricsRegistry()
    h = reg.histogram("bench_lat", "x", buckets=_m.LATENCY_BUCKETS_S)
    c = reg.counter("bench_total", "x")
    g = reg.gauge("bench_last", "x")
    t0 = time.perf_counter()
    for i in range(n):
        h.observe(0.001 * (i % 7 + 1))
        c.inc()
        g.set(0.001)
    return (time.perf_counter() - t0) / n * 1e6


def measure_sweep_telemetry_overhead(
    n_users=20_000, n_items=2_000, n_ratings=200_000, sweeps=16, reps=10
):
    """Per-sweep convergence-telemetry cost as a fraction of device
    sweep time: the SAME synthetic wire trained with the telemetry
    executable and the telemetry-free executable
    (ALSConfig.sweep_telemetry static arg), ``sweeps`` sweeps per run
    so dispatch noise amortizes, ``reps`` timed runs per variant
    INTERLEAVED with the MIN taken per side (see the inline comment —
    sequential medians billed box noise to one variant). The hard gate
    is <2% — the telemetry is two elementwise reductions over the
    factor matrices per sweep, which must stay noise against the
    gather/einsum/Cholesky work."""
    import numpy as np

    from predictionio_tpu.ops.als import (
        ALSConfig,
        build_host_wire,
        train_from_wire,
    )

    rng = np.random.default_rng(11)
    u = rng.integers(0, n_users, n_ratings).astype(np.int32)
    i = rng.integers(0, n_items, n_ratings).astype(np.int32)
    r = (rng.integers(1, 11, n_ratings) / 2.0).astype(np.float32)

    prepared = {}
    for telemetry in (True, False):
        config = ALSConfig(
            rank=8, iterations=sweeps, reg=0.05, sweep_telemetry=telemetry
        )
        prepared[telemetry] = (
            build_host_wire(u, i, r, n_users, n_items, config), config
        )

    def one_loop_s(telemetry: bool) -> float:
        t = {}
        wire, config = prepared[telemetry]
        train_from_wire(wire, config, timings=t)
        return t["device_loop_s"]

    # warm BOTH executables, then interleave the timed reps and take
    # the min — cold-cache effects and box noise land on both sides
    # symmetrically instead of billing whichever variant ran first
    # (a sequential median-of-3 measured a phantom ~3% on the 2-CPU
    # build box; interleaved mins show <0.5%)
    samples = {True: [], False: []}
    for telemetry in (True, False):
        one_loop_s(telemetry)
    for _ in range(reps):
        for telemetry in (True, False):
            samples[telemetry].append(one_loop_s(telemetry))
    with_tel = min(samples[True])
    without = min(samples[False])
    frac = max(0.0, (with_tel - without) / without)
    return {
        "sweep_telemetry_overhead_frac": round(frac, 5),
        "sweep_s_with_telemetry": round(with_tel / sweeps, 5),
        "sweep_s_without_telemetry": round(without / sweeps, 5),
    }


def convergence_curve(timings: dict, digits=5):
    """The per-sweep factor-delta curve [[dx, dy], ...] from a train's
    sweep telemetry (ops/als.py) — the summary-JSON form of the
    registry's pio_train_sweep_factor_delta histogram."""
    tel = timings.get("sweep_telemetry")
    if not tel:
        return None
    return [
        [round(row["dx"], digits), round(row["dy"], digits)] for row in tel
    ]


# --- config 1: recommendation ALS (headline) ---


def bench_recommendation(device_name):
    from predictionio_tpu.ops.als import (
        ALSConfig,
        ServingFactors,
        rmse,
        train_als,
    )
    from predictionio_tpu.ops.als_reference import (
        rmse_reference,
        train_als_reference,
    )

    u, i, r = synth_ml100k()
    config = ALSConfig(rank=RANK, iterations=ITERS, reg=0.05)

    # warm-up: the fused training loop (ops/als.py _run_iterations) takes
    # its trip count as a RUNTIME value, so a 1-iteration run with the same
    # rank/reg compiles the identical executable the timed run reuses
    train_als(
        u, i, r, N_USERS, N_ITEMS,
        ALSConfig(rank=RANK, iterations=1, reg=0.05),
    )

    t0 = time.perf_counter()
    model = train_als(u, i, r, N_USERS, N_ITEMS, config)
    train_s = time.perf_counter() - t0

    train_rmse = rmse(model, u, i, r)

    # MLlib-semantics parity: the float64 numpy oracle on identical data
    # (weighted-lambda ALS-WR, same init scheme/seed)
    X_ref, Y_ref = train_als_reference(
        u, i, r, N_USERS, N_ITEMS, rank=RANK, iterations=ITERS, reg=0.05,
        reg_mode="weighted", seed=0,
    )
    rmse_ref = rmse_reference(X_ref, Y_ref, u, i, r)
    rmse_vs_mllib = abs(train_rmse - rmse_ref)

    # predict latency, split into device compute vs fetch-inclusive.
    # Even block_until_ready pays a full relay round trip on this rig, so
    # the compute number comes from a chained on-device loop whose
    # per-pass time cancels the round trip (ServingFactors.measure_compute_ms).
    serving = ServingFactors(model.user_factors, model.item_factors)
    users = list(range(32))
    rows = model.user_factors[np.asarray(users)]
    # 4096 chained passes: total device time (~0.5 s) must dominate the
    # ±20 ms relay-round-trip jitter or the subtraction estimate drowns
    device_ms = serving.measure_compute_ms(rows, 10, iters=4096)
    serving.topn_by_user(users, 10)  # compile

    # The serving hot path costs exactly ONE blocking device round trip:
    # the query upload (jax.device_put) and the top-N dispatch are both
    # async; the only wait is fetching the single packed result buffer
    # (ops/als.py _topn_packed packs scores+ids into one buffer for this
    # reason). Measure the bare dispatch+fetch round trip of a trivial
    # 8-float program — the floor ANY result-returning call pays on this
    # rig — interleaved with the predict loop so link drift doesn't skew
    # the subtraction.
    import jax
    import jax.numpy as jnp

    tiny = jax.device_put(np.zeros(8, np.float32))
    rtt_probe = jax.jit(lambda x, j: x + j)
    jax.device_get(rtt_probe(tiny, 0.0))
    full_lat, rtt_lat = [], []
    for j in range(50):
        t0 = time.perf_counter()
        serving.topn_by_user(users, 10)
        full_lat.append((time.perf_counter() - t0) * 1000)
        t0 = time.perf_counter()
        jax.device_get(rtt_probe(tiny, float(j)))
        rtt_lat.append((time.perf_counter() - t0) * 1000)
    rtt_p50 = pctl(rtt_lat, 50)

    rest = bench_rest_serving(u, i, r)

    emit(
        {
            "metric": "als_ml100k_train_wall_clock",
            "value": round(train_s, 3),
            "unit": "s",
            "vs_baseline": round(SPARK_LOCAL_ALS_S / train_s, 2),
            "rmse_train": round(train_rmse, 4),
            "rmse_mllib_oracle": round(rmse_ref, 4),
            "rmse_vs_mllib": round(rmse_vs_mllib, 4),
            # parity is vs a float64 oracle of MLlib-1.3 semantics on
            # IDENTICAL synthetic ML-100K-shaped data (zero-egress image;
            # real MovieLens is not redistributable here) — it validates
            # algorithm semantics, not dataset-level reproduction
            "rmse_data": "synthetic-ml100k-shape",
            "predict_device_compute_ms": round(device_ms, 4),
            "predict_p50_ms": round(pctl(full_lat, 50), 2),
            # one documented relay round trip (async upload + async
            # dispatch + ONE blocking result fetch); the bare-RTT floor
            # is measured interleaved, and the remainder is the true
            # device+host serving cost
            "relay_rtt_p50_ms": round(rtt_p50, 2),
            "predict_p50_ms_minus_rtt": round(
                max(pctl(full_lat, 50) - rtt_p50, 0.0), 2
            ),
            "predict_device_round_trips": 1,
            **rest,
            "device": device_name,
        },
        baseline_s=SPARK_LOCAL_ALS_S,
    )


def bench_rest_serving(
    u, i, r, pipeline_depth=4, clients=32, n_requests=12,
    transport="async",
):
    """End-to-end POST /queries.json p50/p99 under concurrent clients
    through the micro-batching executor (api/engine_server.py), on the
    event-loop frontend (api/aio_http.py) by default.

    Throughput here is pipeline-shaped: every batch costs one relay
    round trip (~90-120 ms on this rig), so qps ~= clients / latency
    with latency ~= RTT + queue wait. Depth 4 keeps four batches in
    flight, which hides most of the queue wait; it is the documented
    opt-in for pure engines like the packaged templates. The async
    frontend holds in-flight queries as queue entries (no parked
    threads), so the collector actually fills device batches —
    ``batch_fill_mean`` (served queries / served batches over the timed
    window) proves the coalescing engaged; the r5 threaded frontend sat
    at ~1."""
    from predictionio_tpu.api.engine_server import EngineServer, ServerConfig
    from predictionio_tpu.data import storage as storage_mod
    from predictionio_tpu.data.event import DataMap, Event
    from predictionio_tpu.data.storage.base import App, EngineInstance
    from predictionio_tpu.models.recommendation.engine import (
        recommendation_engine,
    )
    from predictionio_tpu.models.recommendation.evaluation import (
        _engine_params,
    )
    from predictionio_tpu.workflow.context import WorkflowContext
    from predictionio_tpu.workflow.core_workflow import CoreWorkflow
    import datetime as dt

    storage = storage_mod.memory_storage()
    app_id = storage.get_meta_data_apps().insert(App(id=0, name="default"))
    events = storage.get_l_events()
    events.init(app_id)
    for uu, ii, rr in zip(u.tolist(), i.tolist(), r.tolist()):
        events.insert(
            Event(
                event="rate",
                entity_type="user",
                entity_id=f"u{uu}",
                target_entity_type="item",
                target_entity_id=f"i{ii}",
                properties=DataMap({"rating": rr}),
            ),
            app_id,
        )

    now = dt.datetime.now(dt.timezone.utc)
    params = _engine_params(rank=RANK, reg=0.05, eval_k=0)
    CoreWorkflow.run_train(
        recommendation_engine(),
        params,
        EngineInstance(
            id="", status="", start_time=now, end_time=now,
            engine_id="bench", engine_version="1",
            engine_variant="engine.json",
            engine_factory="predictionio_tpu.models.recommendation",
        ),
        ctx=WorkflowContext(mode="training", storage=storage),
    )
    # pipeline_depth > 1 is the documented opt-in for pure engines (the
    # packaged templates): overlaps batch dispatches with result
    # fetches. The default is 1 (reference-parity serial serving).
    server = EngineServer(
        recommendation_engine(),
        ServerConfig(
            port=0, pipeline_depth=pipeline_depth, transport=transport
        ),
        storage=storage,
    ).start()
    try:
        import http.client

        def one_request(conn, uid):
            body = json.dumps({"user": f"u{uid}", "num": 10})
            t0 = time.perf_counter()
            conn.request(
                "POST", "/queries.json", body,
                {"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            resp.read()
            assert resp.status == 200, resp.status
            return (time.perf_counter() - t0) * 1000

        def client(worker, n=n_requests):
            # one persistent HTTP/1.1 connection per client
            conn = http.client.HTTPConnection("localhost", server.port)
            try:
                return [
                    one_request(conn, (worker * 31 + j) % N_USERS)
                    for j in range(n)
                ]
            finally:
                conn.close()

        client(0, 2)  # warm the serving path
        # single-client latency first: the no-coalescing floor a lone
        # caller pays (acceptance guard: the async frontend must not
        # regress the sequential path)
        single = client(0, 20)
        stats_before = server.api._executor.stats()
        scrape_before = scrape_metrics(server.port)
        lat = []
        t0 = time.perf_counter()
        with concurrent.futures.ThreadPoolExecutor(
            max_workers=clients
        ) as pool:
            for chunk in pool.map(client, range(clients)):
                lat.extend(chunk)
        wall = time.perf_counter() - t0
        scrape_after = scrape_metrics(server.port)
        stats_after = server.api._executor.stats()
        served_batches = stats_after["batches"] - stats_before["batches"]
        served_queries = stats_after["queries"] - stats_before["queries"]
        batch_fill_mean = (
            served_queries / served_batches if served_batches else 0.0
        )
        # counter evidence for the bench JSON: the timed window's
        # /metrics deltas (batch-fill histogram + request counter)
        window_metrics = metrics_delta(
            scrape_before, scrape_after,
            ("pio_serving_batch_fill", "pio_serving_requests_total"),
        )

        # In-process serving latency: the SAME request core
        # (QueryAPI.handle — auth-free query route, micro-batching
        # executor, device dispatch, JSON render) with no socket, no
        # HTTP parse, no network relay in the measurement. This is the
        # direct replacement for the fragile predict_p50_ms_minus_rtt
        # subtraction: what serving costs beyond transport, measured
        # instead of inferred.
        def inproc_one(uid):
            body = json.dumps({"user": f"u{uid}", "num": 10}).encode()
            t0 = time.perf_counter()
            status, _, _ = server.api.handle("POST", "/queries.json", {}, body)
            assert status == 200, status
            return (time.perf_counter() - t0) * 1000

        for j in range(5):  # warm
            inproc_one(j)
        inproc = [inproc_one((j * 31) % N_USERS) for j in range(200)]
        # in-proc regression gate for the instrumentation itself: the
        # registry's per-request cost must be noise against the in-proc
        # serving p50 (per-child locks, no registry-wide lock)
        overhead_us = measure_metrics_overhead_us()
        inproc_p50_us = pctl(inproc, 50) * 1000.0
        assert overhead_us < 50.0, (
            f"registry overhead {overhead_us:.1f}us/request — serving "
            "instrumentation must stay in the single-digit-us range"
        )
        assert overhead_us < 0.05 * inproc_p50_us, (
            f"registry overhead {overhead_us:.1f}us is no longer noise "
            f"against the in-proc serving p50 ({inproc_p50_us:.0f}us)"
        )

        # liveness latency gate: /healthz is what orchestrators poll at
        # high frequency across a fleet — it must answer in sub-ms. The
        # gated figure is the request-core cost (handler dispatch +
        # liveness payload, no socket); the keep-alive HTTP round trip
        # is reported beside it for the end-to-end picture.
        def healthz_one():
            t0 = time.perf_counter()
            status, _, _ = server.api.handle("GET", "/healthz")
            assert status == 200, status
            return (time.perf_counter() - t0) * 1000

        for _ in range(20):
            healthz_one()
        healthz_ms = [healthz_one() for _ in range(300)]
        healthz_p50_ms = pctl(healthz_ms, 50)
        assert healthz_p50_ms < 1.0, (
            f"/healthz p50 {healthz_p50_ms:.3f}ms — liveness must stay "
            "sub-millisecond (no storage/daemon consultation allowed "
            "on this route)"
        )
        hconn = http.client.HTTPConnection("localhost", server.port)
        try:
            http_healthz = []
            for _ in range(50):
                t0 = time.perf_counter()
                hconn.request("GET", "/healthz")
                resp = hconn.getresponse()
                resp.read()
                assert resp.status == 200, resp.status
                http_healthz.append((time.perf_counter() - t0) * 1000)
        finally:
            hconn.close()
        return {
            "rest_p50_ms": round(pctl(lat, 50), 2),
            "rest_p99_ms": round(pctl(lat, 99), 2),
            "rest_qps": round(len(lat) / wall, 1),
            "rest_clients": clients,
            "rest_pipeline_depth": pipeline_depth,
            "rest_transport": transport,
            # mean served-batch fill over the concurrent window: > 1
            # means micro-batches actually coalesced into one device
            # predict (the ALX-style [B,k]x[k,n] throughput story)
            "batch_fill_mean": round(batch_fill_mean, 2),
            "rest_single_client_p50_ms": round(pctl(single, 50), 2),
            "predict_inproc_p50_ms": round(pctl(inproc, 50), 2),
            "predict_inproc_p99_ms": round(pctl(inproc, 99), 2),
            "predict_inproc_qps": round(1000.0 / max(pctl(inproc, 50), 1e-6), 1),
            "metrics_overhead_us_per_request": round(overhead_us, 2),
            "metrics_window_delta": window_metrics,
            "healthz_p50_ms": round(healthz_p50_ms, 4),
            "healthz_rest_p50_ms": round(pctl(http_healthz, 50), 3),
        }
    finally:
        server.shutdown()


# --- config 6: north-star scale — ML-20M-shaped ALS with MFU ---


def synth_ml20m(n_users, n_items, n_ratings, seed=41):
    """MovieLens-20M-shaped synthetic ratings (the real dataset is not
    redistributable in this image): low-rank-plus-noise scores on a
    lognormal-activity x zipf-popularity long tail, snapped to ML-20M's
    0.5-step 0.5..5.0 rating scale."""
    rng = np.random.default_rng(seed)
    k0 = 12
    U = (rng.standard_normal((n_users, k0)) / np.sqrt(k0)).astype(np.float32)
    V = (rng.standard_normal((n_items, k0)) / np.sqrt(k0)).astype(np.float32)
    u_p = rng.lognormal(0, 1.1, n_users)
    u_p /= u_p.sum()
    i_p = 1.0 / np.arange(1, n_items + 1) ** 0.9
    i_p /= i_p.sum()
    u = rng.choice(n_users, size=n_ratings, p=u_p).astype(np.int32)
    i = rng.choice(n_items, size=n_ratings, p=i_p).astype(np.int32)
    raw = np.empty(n_ratings, np.float32)
    for s in range(0, n_ratings, 4_000_000):  # chunk the 20M-row gather
        e = min(s + 4_000_000, n_ratings)
        raw[s:e] = np.einsum("nk,nk->n", U[u[s:e]], V[i[s:e]])
    scores = 3.0 + 1.3 * raw + 0.5 * rng.standard_normal(n_ratings)
    r = np.clip(np.round(scores * 2.0) / 2.0, 0.5, 5.0).astype(np.float32)
    return u, i, r


def bench_ml20m(device_name):
    """The north-star config at its stated scale: 138k x 27k x 20M ALS,
    rank 32, 10 iterations, single chip. Reports the phase-split wall
    clock, peak HBM, achieved FLOP/s and MFU (vs the published bf16 peak
    of the chip), plus RMSE parity vs the float64 MLlib oracle on a
    subsampled slice (the oracle is O(minutes) at full scale)."""
    from predictionio_tpu.ops.als import (
        ALSConfig,
        predict_ratings,
        train_als,
    )
    from predictionio_tpu.ops.als_reference import (
        rmse_reference,
        train_als_reference,
    )
    import jax

    n_users, n_items = 138_493, 26_744
    n_ratings = int(os.environ.get("BENCH_ML20M_RATINGS", 20_000_000))
    rank, iters, reg = 32, 10, 0.05

    u, i, r = synth_ml20m(n_users, n_items, n_ratings)

    config = ALSConfig(
        rank=rank, iterations=iters, reg=reg,
        compute_dtype="bfloat16",  # MXU-rate einsums, f32 accumulation
    )

    # one call does everything: train_als compiles via a zero-iteration
    # run before its timed loop (timings["compile_s"]), so no separate
    # warm-up pass re-packs and re-transfers the ~1 GB of segment data
    timings = {}
    t0 = time.perf_counter()
    model = train_als(u, i, r, n_users, n_items, config, timings=timings)
    total_s = time.perf_counter() - t0
    loop_s = timings.get("device_loop_s", total_s)
    # grid slots both sides, incl. chunk-grid padding segments — the true
    # denominator for hardware busyness
    slots = timings.get("padded_slots", 0)

    # model FLOPs (real observations only — padding work is excluded, so
    # this is true MFU, not hardware busyness): per observation per side,
    # the Gramian-correction einsum is k^2 MACs and the rhs k MACs
    flops_per_slot = 2 * rank * rank + 2 * rank
    model_flops = 2 * n_ratings * flops_per_slot * iters
    padded_flops = slots * flops_per_slot * iters
    achieved = model_flops / loop_s
    peak = PEAK_BF16_FLOPS.get(jax.devices()[0].device_kind)

    # Memory-bound roofline for the device loop. ALS at rank 32 does
    # ~2k^2 FLOPs per 128-byte gathered row — arithmetic intensity ~16
    # FLOP/byte against an MXU that needs ~240 at bf16 peak, so the loop
    # is bound by data movement, and MFU is structurally tiny no matter
    # how well it runs. The two dominant movers, with their own ceilings:
    #   gather: every slot gathers one factor row per iteration; TPU
    #     gathers are row-rate bound (measured live below; ~420 Mrows/s
    #     on v5e, ~6% of HBM byte peak — a lowering property, not a
    #     tuning gap).
    #   solve:  the in-place batched Cholesky makes k passes over the
    #     [R, k, k] systems per side per iteration (read + write)
    #     — pure streaming, judged against HBM peak. Measured in
    #     isolation it runs at ~310 GB/s = ~38% of v5e peak.
    gather_ceiling_mrows = measure_gather_ceiling_mrows(n_items + 1, rank)
    gather_floor_s = slots * iters / (gather_ceiling_mrows * 1e6)
    hbm_peak = PEAK_HBM_GBPS.get(jax.devices()[0].device_kind)
    solve_bytes = (
        iters * rank * 2 * 4  # k passes, read+write, f32
        * ((n_users + 1) + (n_items + 1)) * rank * rank
    )
    solve_floor_s = solve_bytes / (hbm_peak * 1e9) if hbm_peak else None
    roofline_s = (
        gather_floor_s + solve_floor_s if solve_floor_s is not None else None
    )

    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        peak_hbm_gb = round(stats.get("peak_bytes_in_use", 0) / 2**30, 3)
        peak_hbm_gb = peak_hbm_gb or None  # relayed devices report 0
    except Exception:
        peak_hbm_gb = None

    # train-RMSE on a 2M-pair sample (full 20M predict is 20 relay trips)
    rng = np.random.default_rng(43)
    idx = rng.choice(n_ratings, size=min(2_000_000, n_ratings), replace=False)
    err = predict_ratings(model, u[idx], i[idx]) - r[idx]
    rmse_train = float(np.sqrt(np.mean(err * err)))

    # MLlib-semantics parity on a subsampled slice: the head of both long
    # tails (ids are popularity-ordered in the generator), full float64
    # oracle vs the TPU kernel in float32 on identical data
    sub = (u < 3000) & (i < 2000)
    su, si, sr = u[sub], i[sub], r[sub]
    if len(su) > 150_000:
        keep = rng.choice(len(su), size=150_000, replace=False)
        su, si, sr = su[keep], si[keep], sr[keep]
    sub_cfg = ALSConfig(rank=rank, iterations=iters, reg=reg)
    sub_model = train_als(su, si, sr, 3000, 2000, sub_cfg)
    sub_rmse = float(
        np.sqrt(np.mean((predict_ratings(sub_model, su, si) - sr) ** 2))
    )
    X_ref, Y_ref = train_als_reference(
        su, si, sr, 3000, 2000, rank=rank, iterations=iters, reg=reg,
        reg_mode="weighted", seed=0,
    )
    rmse_ref = rmse_reference(X_ref, Y_ref, su, si, sr)

    emit(
        {
            "metric": "als_ml20m_train_wall_clock",
            "value": round(total_s, 3),
            "unit": "s",
            "vs_baseline": round(SPARK_LOCAL_ALS_ML20M_S / total_s, 2),
            "n_users": n_users,
            "n_items": n_items,
            "n_ratings": n_ratings,
            "rank": rank,
            "iterations": iters,
            "pack_s": round(timings.get("pack_s", 0.0), 3),
            "compile_s": round(timings.get("compile_s", 0.0), 3),
            "device_put_s": round(timings.get("device_put_s", 0.0), 3),
            "wire_mb": timings.get("wire_mb"),
            "device_pack_dispatch_s": round(
                timings.get("device_pack_dispatch_s", 0.0), 3
            ),
            "device_loop_s": round(loop_s, 3),
            # memory-bound roofline (see comments above): modeled floor =
            # gather rows at the live-measured gather ceiling + Cholesky
            # streaming at HBM peak. loop_vs_roofline ~1 would mean the
            # loop runs at the hardware's own per-op limits.
            "gather_ceiling_mrows_per_s": round(gather_ceiling_mrows),
            "loop_gather_mrows_per_s": round(slots * iters / loop_s / 1e6),
            "loop_roofline_s": round(roofline_s, 2) if roofline_s else None,
            "loop_vs_roofline": (
                round(loop_s / roofline_s, 2) if roofline_s else None
            ),
            "model_tflops": round(model_flops / 1e12, 2),
            "achieved_tflops_per_s": round(achieved / 1e12, 2),
            "mfu": round(achieved / peak, 4) if peak else None,
            "hw_util_incl_padding": (
                round(padded_flops / loop_s / peak, 4) if peak else None
            ),
            "peak_flops_assumed_tflops": round(peak / 1e12) if peak else None,
            "peak_hbm_gb": peak_hbm_gb,
            "rmse_train_2m_sample": round(rmse_train, 4),
            "rmse_subsample": round(sub_rmse, 4),
            "rmse_mllib_oracle_subsample": round(rmse_ref, 4),
            "rmse_vs_mllib_subsample": round(abs(sub_rmse - rmse_ref), 4),
            # per-sweep [user, item] factor-delta RMS from the fused
            # loop's telemetry output — the convergence curve behind
            # device_loop_s (cost <2% of sweep time, gated in
            # delta_train's dedicated overhead measure)
            "convergence": convergence_curve(timings),
            "device": device_name,
        },
        baseline_s=SPARK_LOCAL_ALS_ML20M_S,
    )


def trace_als_loop(device_name, out_path="docs/ALS_LOOP_TRACE.json"):
    """Capture a jax.profiler trace of EXACTLY the ML-20M device loop and
    reduce it to a committed per-op attribution table (round-4 verdict
    weak #1: the loop-vs-roofline residual was asserted, not shown).

    Run via ``python bench.py --trace-loop`` on TPU hardware. The trace
    context wraps only the timed loop inside train_als (profile_dir), so
    the table attributes the loop wall clock alone — no pack, transfer or
    compile events. Ops aggregate by (hlo_category, op name); while-loop
    container events are kept (marked nested=true) for structure but
    excluded from the leaf total.
    """
    import glob
    import gzip
    import shutil
    import tempfile
    from collections import defaultdict

    from predictionio_tpu.ops.als import ALSConfig, train_als

    n_users, n_items = 138_493, 26_744
    n_ratings = int(os.environ.get("BENCH_ML20M_RATINGS", 20_000_000))
    rank = int(os.environ.get("BENCH_ML20M_RANK", 32))
    iters = int(os.environ.get("BENCH_ML20M_ITERS", 10))
    u, i, r = synth_ml20m(n_users, n_items, n_ratings)
    config = ALSConfig(
        rank=rank, iterations=iters, reg=0.05, compute_dtype="bfloat16"
    )
    tmp = tempfile.mkdtemp(prefix="als_trace_")
    timings = {}
    try:
        train_als(
            u, i, r, n_users, n_items, config,
            timings=timings, profile_dir=tmp,
        )
        trace_files = sorted(
            glob.glob(
                os.path.join(tmp, "**", "*.trace.json.gz"), recursive=True
            )
        )
        if not trace_files:
            raise RuntimeError(
                "profiler produced no trace — the device loop never ran "
                "(iterations=0, or a resume past the requested count?)"
            )
        data = json.load(gzip.open(trace_files[-1]))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    events = data["traceEvents"]
    pids = {
        e.get("pid"): e["args"].get("name", "")
        for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    tpu_pids = {p for p, n in pids.items() if "TPU" in str(n)}
    if not tpu_pids:
        raise RuntimeError(
            f"no TPU device lane in the trace (processes: {pids}) — "
            "--trace-loop must run on TPU hardware"
        )
    agg = defaultdict(lambda: [0.0, 0, 0, 0])
    for e in events:
        args = e.get("args", {})
        if (
            e.get("ph") == "X"
            and e.get("pid") in tpu_pids
            and "device_duration_ps" in args
        ):
            key = (args.get("hlo_category", "?"), e["name"].split("(")[0])
            agg[key][0] += e["dur"] / 1e3
            agg[key][1] += 1
            agg[key][2] += int(args.get("bytes_accessed", 0))
            agg[key][3] += int(args.get("model_flops", 0) or 0)

    def nested(cat, name):
        # containers double-count their leaves: the jit wrapper and the
        # while bodies (iteration loop + per-side chunk/solve loops)
        return cat == "while" or name.startswith("jit_")

    leaf_ms = sum(
        v[0] for (c, n), v in agg.items() if not nested(c, n)
    )
    if not agg or leaf_ms <= 0.0:
        raise RuntimeError(
            "trace captured no attributable device op time — refusing to "
            "write an empty attribution table"
        )
    ops = []
    for (cat, name), (ms, cnt, b, fl) in sorted(
        agg.items(), key=lambda kv: -kv[1][0]
    ):
        is_nested = nested(cat, name)
        ops.append(
            {
                "op": name,
                "hlo_category": cat,
                "total_ms": round(ms, 1),
                "pct_of_leaf": (
                    None if is_nested else round(100 * ms / leaf_ms, 1)
                ),
                "count": cnt,
                "bytes_accessed_gib": round(b / 2**30, 2),
                "gb_per_s": (
                    round(b / 2**30 * 1.074 / (ms / 1e3), 1) if ms else None
                ),
                "model_gflops": round(fl / 1e9, 1),
                "nested": is_nested,
            }
        )
    record = {
        "metric": "als_ml20m_loop_trace",
        "n_ratings": n_ratings,
        "rank": rank,
        "iterations": iters,
        "device_loop_s": round(timings.get("device_loop_s", 0.0), 3),
        "leaf_device_time_s": round(leaf_ms / 1e3, 3),
        "padded_slots": timings.get("padded_slots"),
        "device": device_name,
        "ops": ops[:24],
    }
    with open(out_path, "w") as f:
        json.dump(record, f, indent=1)
    print(json.dumps({k: v for k, v in record.items() if k != "ops"}))
    for o in ops[:14]:
        print(
            f"  {o['total_ms']:9.1f} ms  {str(o['pct_of_leaf'] or ''):>5}%  "
            f"n={o['count']:5d}  {o['bytes_accessed_gib']:8.2f} GiB  "
            f"{o['hlo_category']:24s} {o['op'][:48]}"
        )
    print(f"wrote {out_path}")


# --- config 6b: the flagship flow THROUGH THE EVENT STORE ---


def bench_ml20m_store(device_name):
    """ML-20M through the real framework path: bulk-import 20M rate
    events into the sqlite event store (columnar pages,
    LEvents.insert_columns), then train THROUGH the streaming
    store→device pipeline (``ops/streaming``): chunked page scan on a
    background thread, incremental pack fold under the scan, counting-
    sort merge, double-buffered async device_put, compile hidden under
    scan+pack — the role of the reference's HBase-scan-feeds-Spark
    flagship flow (hbase/HBPEvents.scala:84-90), now pipelined instead
    of a serial scan→pack→put→compile chain.

    value = the COLD streaming store→model wall (what `pio train` costs
    with data at rest and an empty pack cache). A second, WARM train
    measures the pack-artifact-cache hit path (unchanged store ⇒ scan+
    pack skipped entirely). ``train_pack_exposed_s`` /
    ``train_device_put_exposed_s`` are the critical-path (non-
    overlapped) remainders of the phases the r05 serial chain paid in
    full (pack 7.1 s + put 4.9 s = 12.0 s).

    MLlib-oracle parity runs on a SECOND app at tractable scale (the
    float64 oracle is O(minutes) at 20M), with zero-padded ids so the
    dense id order matches the oracle's integer order — cold (cache
    miss) and warm (cache hit) streaming paths both check against it."""
    import shutil
    import tempfile

    from predictionio_tpu.data.storage import Storage
    from predictionio_tpu.data.storage.base import App
    from predictionio_tpu.data.store import PEventStore
    from predictionio_tpu.models.recommendation.engine import RATING_SPEC
    from predictionio_tpu.ops.als import ALSConfig, predict_ratings
    from predictionio_tpu.ops.als_reference import (
        rmse_reference,
        train_als_reference,
    )
    from predictionio_tpu.ops.streaming import (
        pack_cache_clear,
        train_als_streaming,
    )

    n_users, n_items = 138_493, 26_744
    n_ratings = int(
        os.environ.get(
            "BENCH_ML20M_STORE_RATINGS",
            os.environ.get("BENCH_ML20M_RATINGS", 20_000_000),
        )
    )
    u, i, r = synth_ml20m(n_users, n_items, n_ratings)
    users = np.char.add("u", u.astype("U7"))
    items = np.char.add("i", i.astype("U6"))

    tmp = tempfile.mkdtemp(prefix="bench_store_")
    try:
        storage = Storage(
            {
                "PIO_STORAGE_SOURCES_SQLITE_TYPE": "sqlite",
                "PIO_STORAGE_SOURCES_SQLITE_PATH": os.path.join(tmp, "s.db"),
                "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQLITE",
                "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQLITE",
                "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQLITE",
            }
        )
        storage.get_meta_data_apps().insert(App(id=0, name="bench"))
        events = storage.get_l_events()
        events.init(1)

        t0 = time.perf_counter()
        events.insert_columns(
            1, event="rate", entity_type="user", target_entity_type="item",
            entity_ids=users, target_ids=items, values=r,
        )
        import_s = time.perf_counter() - t0

        store = PEventStore(storage)
        scan_kwargs = dict(
            value_spec=RATING_SPEC,
            entity_type="user",
            target_entity_type="item",
            event_names=["rate", "buy"],
        )
        config = ALSConfig(
            rank=32, iterations=10, reg=0.05, compute_dtype="bfloat16"
        )

        pack_cache_clear()
        timings = {}
        t0 = time.perf_counter()
        res = train_als_streaming(
            store.stream_columns("bench", **scan_kwargs),
            config, timings=timings,
        )
        cold_s = time.perf_counter() - t0
        assert res is not None, "store must be streamable for this bench"

        warm = {}
        t0 = time.perf_counter()
        res_w = train_als_streaming(
            store.stream_columns("bench", **scan_kwargs),
            config, timings=warm,
        )
        warm_s = time.perf_counter() - t0
        warm_factors_equal = bool(
            np.array_equal(res.arrays.user_factors, res_w.arrays.user_factors)
            and np.array_equal(
                res.arrays.item_factors, res_w.arrays.item_factors
            )
        )

        # MLlib-oracle parity at tractable scale, through the SAME
        # streaming store path: head of both popularity tails, ids
        # zero-padded so sorted-name dense order == the oracle's integer
        # order (row-indexed init then matches exactly)
        sub = (u < 3000) & (i < 2000)
        su, si, sr = u[sub], i[sub], r[sub]
        if len(su) > 150_000:
            keep = np.random.default_rng(43).choice(
                len(su), size=150_000, replace=False
            )
            su, si, sr = su[keep], si[keep], sr[keep]
        storage.get_meta_data_apps().insert(App(id=0, name="bench-parity"))
        parity_app = storage.get_meta_data_apps().get_by_name("bench-parity")
        events.init(parity_app.id)
        events.insert_columns(
            parity_app.id, event="rate", entity_type="user",
            target_entity_type="item",
            entity_ids=np.array([f"u{v:05d}" for v in su]),
            target_ids=np.array([f"i{v:05d}" for v in si]),
            values=sr,
        )
        sub_cfg = ALSConfig(rank=32, iterations=10, reg=0.05)

        def stream_sub_rmse():
            sres = train_als_streaming(
                store.stream_columns("bench-parity", **scan_kwargs),
                sub_cfg, timings={},
            )
            uidx = np.fromiter(
                (sres.user_index[f"u{v:05d}"] for v in su),
                np.int32, count=len(su),
            )
            iidx = np.fromiter(
                (sres.item_index[f"i{v:05d}"] for v in si),
                np.int32, count=len(si),
            )
            err = predict_ratings(sres.arrays, uidx, iidx) - sr
            return float(np.sqrt(np.mean(err * err))), sres

        rmse_cold, sres_cold = stream_sub_rmse()
        rmse_warm, _ = stream_sub_rmse()  # pack-cache hit path
        # oracle on the DENSE rank space (unique-sorted = the store's
        # sorted zero-padded names), so row-indexed init lines up
        uniq_u, su_d = np.unique(su, return_inverse=True)
        uniq_i, si_d = np.unique(si, return_inverse=True)
        X_ref, Y_ref = train_als_reference(
            su_d, si_d, sr, len(uniq_u), len(uniq_i),
            rank=32, iterations=10, reg=0.05, reg_mode="weighted", seed=0,
        )
        rmse_ref = rmse_reference(X_ref, Y_ref, su_d, si_d, sr)

        exposed_pack = timings.get("pack_exposed_s", 0.0)
        exposed_put = timings.get("device_put_exposed_s", 0.0)
        emit(
            {
                "metric": "als_ml20m_store_to_model_wall_clock",
                "value": round(cold_s, 3),
                "unit": "s",
                "vs_baseline": round(SPARK_LOCAL_ALS_ML20M_S / cold_s, 2),
                "n_ratings": n_ratings,
                "import_s": round(import_s, 3),
                # overlapped (busy) phase attribution: the scan and the
                # per-batch pack fold ran UNDER each other; compile ran
                # under merge+transfer
                "store_scan_s": round(timings.get("scan_s", 0.0), 3),
                "train_s": round(cold_s, 3),
                "train_pack_s": round(timings.get("pack_s", 0.0), 3),
                "train_fold_overlapped_s": round(
                    timings.get("fold_s", 0.0), 3
                ),
                # critical-path (exposed) remainders — the acceptance
                # target: exposed pack+put vs the r05 serial 12.0 s
                "train_pack_exposed_s": round(exposed_pack, 3),
                "train_device_put_exposed_s": round(exposed_put, 3),
                "train_pack_put_exposed_s": round(
                    exposed_pack + exposed_put, 3
                ),
                "r05_serial_pack_put_s": 12.0,
                "train_wire_mb": timings.get("wire_mb"),
                "train_compile_s": round(timings.get("compile_s", 0.0), 3),
                "train_compile_exposed_s": round(
                    timings.get("compile_exposed_s", 0.0), 3
                ),
                "train_device_loop_s": round(
                    timings.get("device_loop_s", 0.0), 3
                ),
                # pack-artifact cache: cold=miss, warm=hit (store
                # unchanged between the two trains)
                "pack_cache": {
                    "cold": timings.get("pack_cache"),
                    "warm": warm.get("pack_cache"),
                    "warm_train_s": round(warm_s, 3),
                    "warm_factors_equal_cold": warm_factors_equal,
                },
                "pack_cache_cold": timings.get("pack_cache"),
                "pack_cache_warm": warm.get("pack_cache"),
                "warm_train_s": round(warm_s, 3),
                # oracle parity through the streaming path, both cache
                # paths (sub-app scale; float64 MLlib-semantics oracle)
                "rmse_stream_cold": round(rmse_cold, 4),
                "rmse_stream_warm": round(rmse_warm, 4),
                "rmse_mllib_oracle": round(rmse_ref, 4),
                "rmse_vs_mllib": round(
                    max(
                        abs(rmse_cold - rmse_ref), abs(rmse_warm - rmse_ref)
                    ),
                    4,
                ),
                "distinct_users": len(res.user_index),
                "distinct_items": len(res.item_index),
                "events_scanned_per_s": (
                    round(n_ratings / timings["scan_s"])
                    if timings.get("scan_s")
                    else None
                ),
                "device": device_name,
            },
            baseline_s=SPARK_LOCAL_ALS_ML20M_S,
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


# --- config 7: Event Server ingestion throughput ---


def _run_ingest_clients(
    port: int, n_clients: int, n_per_client: int, batch_size: int = 1
):
    """Shared POST-client harness for the ingestion configs: warm one
    client, then fan out ``n_clients`` concurrent clients posting
    ``n_per_client`` EVENTS each. ``batch_size`` 1 posts per-event
    ``/events.json``; > 1 (<= 50) posts ``/batch/events.json`` groups —
    each request one group-commit unit. Returns
    (request_latencies_ms, n_events, wall_s). Kept in one place so the
    scan-free and scan-in-flight configs can never drift into measuring
    different protocols."""
    import http.client

    assert 1 <= batch_size <= 50

    def event_json(worker, j):
        return {
            "event": "rate",
            "entityType": "user",
            "entityId": f"u{worker}-{j}",
            "targetEntityType": "item",
            "targetEntityId": f"i{j % 97}",
            "properties": {"rating": float(j % 5 + 1)},
        }

    def client(worker):
        conn = http.client.HTTPConnection("localhost", port)
        lat = []
        sent = 0
        try:
            for s in range(0, n_per_client, batch_size):
                group = [
                    event_json(worker, j)
                    for j in range(s, min(s + batch_size, n_per_client))
                ]
                if batch_size == 1:
                    path, body = (
                        "/events.json?accessKey=benchkey",
                        json.dumps(group[0]),
                    )
                else:
                    path, body = (
                        "/batch/events.json?accessKey=benchkey",
                        json.dumps(group),
                    )
                t0 = time.perf_counter()
                conn.request(
                    "POST", path, body, {"Content-Type": "application/json"}
                )
                resp = conn.getresponse()
                data = resp.read()
                if batch_size == 1:
                    assert resp.status == 201, resp.status
                else:
                    assert resp.status == 200, resp.status
                    statuses = [r["status"] for r in json.loads(data)]
                    assert statuses == [201] * len(group), statuses
                lat.append((time.perf_counter() - t0) * 1000)
                sent += len(group)
        finally:
            conn.close()
        return lat, sent

    client(999)  # warm (threads, code paths)
    lat = []
    n_events = 0
    t0 = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(
        max_workers=n_clients
    ) as pool:
        for chunk, sent in pool.map(client, range(n_clients)):
            lat.extend(chunk)
            n_events += sent
    return lat, n_events, time.perf_counter() - t0


def bench_ingestion(device_name):
    """POST /events.json throughput under concurrent clients — the Event
    Server is the reference's front door (EventServer.scala:502) and its
    write path (auth -> validation -> storage insert) is pure host work.
    Memory-backed storage isolates server overhead from disk."""
    from predictionio_tpu.api.event_server import (
        EventServer,
        EventServerConfig,
    )
    from predictionio_tpu.data import storage as storage_mod
    from predictionio_tpu.data.storage.base import AccessKey, App

    storage = storage_mod.memory_storage()
    app_id = storage.get_meta_data_apps().insert(App(id=0, name="bench"))
    storage.get_meta_data_access_keys().insert(
        AccessKey(key="benchkey", appid=app_id, events=())
    )
    storage.get_l_events().init(app_id)
    server = EventServer(
        storage=storage, config=EventServerConfig(port=0)
    ).start()
    try:
        # headline: the batch route (each request one <=50-event
        # group-commit unit) — the protocol a client at "millions of
        # users" scale is expected to speak
        n_clients, batch_size = 16, 50
        n_per_client = 3000
        scrape_before = scrape_metrics(server.port)
        blat, n_events, bwall = _run_ingest_clients(
            server.port, n_clients, n_per_client, batch_size=batch_size
        )
        ingest_metrics = metrics_delta(
            scrape_before, scrape_metrics(server.port),
            ("pio_events_ingested_total", "pio_group_commit"),
        )
        # per-event POSTs ride along so the protocol overhead stays
        # visible (and regression-watched) next to the batch rate
        slat, s_events, swall = _run_ingest_clients(
            server.port, n_clients, 150, batch_size=1
        )
        emit(
            {
                "metric": "eventserver_ingest_events_per_sec",
                "value": round(n_events / bwall, 1),
                "unit": "events/s",
                # the reference publishes no ingestion numbers; a
                # single-node spray/HBase event server is commonly cited
                # around ~1k events/s — conservative stand-in
                "vs_baseline": round(n_events / bwall / 1000.0, 2),
                "baseline_events_per_sec": 1000,
                "baseline_estimated": True,
                "batch_size": batch_size,
                "ingest_p50_ms": round(pctl(blat, 50), 2),
                "ingest_p99_ms": round(pctl(blat, 99), 2),
                "single_event_events_per_sec": round(s_events / swall, 1),
                "single_ingest_p50_ms": round(pctl(slat, 50), 2),
                "single_ingest_p99_ms": round(pctl(slat, 99), 2),
                "clients": n_clients,
                "metrics_window_delta": ingest_metrics,
                "device": device_name,
            }
        )
    finally:
        server.shutdown()


# --- config 7b: ingestion racing a training scan (sqlite WAL) ---


def bench_concurrent_ingest(device_name):
    """POST /events.json throughput while a training scan loops over the
    same sqlite-backed store — the concurrency contract of the
    reference's HBase tier (ingest and region-parallel scans proceed
    together, hbase/StorageClient.scala:40). Measures the WAL
    snapshot-read design: scans run on per-thread read connections, so
    ingest throughput under a scan should hold near the scan-free rate."""
    import shutil
    import tempfile
    import threading

    from predictionio_tpu.api.event_server import (
        EventServer,
        EventServerConfig,
    )
    from predictionio_tpu.data.storage import Storage
    from predictionio_tpu.data.storage.base import AccessKey, App
    from predictionio_tpu.data.store import PEventStore
    from predictionio_tpu.models.recommendation.engine import RATING_SPEC

    tmp = tempfile.mkdtemp(prefix="bench_conc_")
    try:
        n_shards = int(os.environ.get("BENCH_INGEST_SHARDS", 4))
        storage = Storage(
            {
                "PIO_STORAGE_SOURCES_SQLITE_TYPE": "sqlite",
                "PIO_STORAGE_SOURCES_SQLITE_PATH": os.path.join(tmp, "s.db"),
                # hash-sharded row stores: K independent WAL write slots,
                # each with its own group committer (ISSUE 2 tentpole)
                "PIO_STORAGE_SOURCES_SQLITE_SHARDS": str(n_shards),
                "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQLITE",
                "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQLITE",
                "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQLITE",
            }
        )
        app_id = storage.get_meta_data_apps().insert(
            App(id=0, name="bench")
        )
        storage.get_meta_data_access_keys().insert(
            AccessKey(key="benchkey", appid=app_id, events=())
        )
        events = storage.get_l_events()
        events.init(app_id)
        # pre-seed bulk pages so the in-flight scan does real work
        rng = np.random.default_rng(7)
        n_seed = 1_000_000
        events.insert_columns(
            app_id, event="rate", entity_type="user",
            target_entity_type="item",
            entity_ids=np.char.add(
                "u", rng.integers(0, 20_000, n_seed).astype("U6")
            ),
            target_ids=np.char.add(
                "i", rng.integers(0, 2_000, n_seed).astype("U5")
            ),
            values=(np.round(rng.uniform(1, 10, n_seed)) / 2).astype(
                np.float32
            ),
        )
        server = EventServer(
            storage=storage, config=EventServerConfig(port=0)
        ).start()
        try:
            n_clients, n_per_client, batch_size = 16, 2000, 50
            stop = threading.Event()
            scans = {"count": 0, "events": 0}
            scan_errors = []

            def scanner():
                p = PEventStore(storage)
                try:
                    while not stop.is_set():
                        cols = p.find_columns(
                            "bench",
                            value_spec=RATING_SPEC,
                            entity_type="user",
                            target_entity_type="item",
                            event_names=["rate", "buy"],
                        )
                        scans["count"] += 1
                        scans["events"] += cols.n
                except Exception as e:
                    scan_errors.append(e)

            scan_t = threading.Thread(target=scanner)
            scan_t.start()
            scrape_before = scrape_metrics(server.port)
            lat, n_events, wall = _run_ingest_clients(
                server.port, n_clients, n_per_client,
                batch_size=batch_size,
            )
            # sqlite backing: the window's per-shard group-commit flush
            # count/rows land in the bench JSON as counter deltas
            ingest_metrics = metrics_delta(
                scrape_before, scrape_metrics(server.port),
                ("pio_events_ingested_total", "pio_group_commit"),
            )
            stop.set()
            scan_t.join(timeout=60)
            # the config exists to measure ingest UNDER scans: a dead or
            # never-completing scanner would silently measure the
            # scan-free rate instead
            if scan_errors:
                raise RuntimeError(f"in-flight scan failed: {scan_errors[0]}")
            assert scans["count"] > 0, "no scan completed during ingest"
            emit(
                {
                    "metric": "concurrent_ingest_events_per_sec",
                    "value": round(n_events / wall, 1),
                    "unit": "events/s",
                    # same conservative single-node stand-in as the
                    # scan-free ingestion config
                    "vs_baseline": round(n_events / wall / 1000.0, 2),
                    "baseline_events_per_sec": 1000,
                    "baseline_estimated": True,
                    "shards": n_shards,
                    "batch_size": batch_size,
                    "ingest_p50_ms": round(pctl(lat, 50), 2),
                    "ingest_p99_ms": round(pctl(lat, 99), 2),
                    "clients": n_clients,
                    "scans_completed_in_flight": scans["count"],
                    "events_scanned_in_flight": scans["events"],
                    "seeded_events": n_seed,
                    "metrics_window_delta": ingest_metrics,
                    "device": device_name,
                }
            )
        finally:
            server.shutdown()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


# --- config 7c: model-quality observability (ISSUE 11) ---


def measure_attribution_overhead(
    n_batches: int = 60, batch_size: int = 50, reps: int = 5
):
    """Ingest-path cost of the online feedback join, as a fraction of
    /batch/events.json throughput: the SAME in-proc batch workload
    against an EventAPI with the commit-hook attribution observer
    enabled vs disabled, reps INTERLEAVED with the min taken per side
    (box noise lands on both symmetrically). The hard gate is <2% —
    the observer is two attribute checks per event for events that
    carry no prId, which is the overwhelming ingest majority."""
    from predictionio_tpu.api.event_server import EventAPI, EventServerConfig
    from predictionio_tpu.data import storage as storage_mod
    from predictionio_tpu.data.storage.base import AccessKey, App

    def make_api(attribution: bool) -> EventAPI:
        storage = storage_mod.memory_storage()
        app_id = storage.get_meta_data_apps().insert(App(id=0, name="q"))
        storage.get_meta_data_access_keys().insert(
            AccessKey(key="k", appid=app_id, events=())
        )
        storage.get_l_events().init(app_id)
        return EventAPI(
            storage=storage,
            config=EventServerConfig(port=0, attribution=attribution),
        )

    apis = {True: make_api(True), False: make_api(False)}
    payloads = [
        json.dumps([
            {
                "event": "rate",
                "entityType": "user",
                "entityId": f"u{b}-{j}",
                "targetEntityType": "item",
                "targetEntityId": f"i{j % 97}",
                "properties": {"rating": float(j % 5 + 1)},
            }
            for j in range(batch_size)
        ]).encode()
        for b in range(n_batches)
    ]

    def one_window_s(attribution: bool) -> float:
        api = apis[attribution]
        t0 = time.perf_counter()
        for body in payloads:
            status, results = api.handle(
                "POST", "/batch/events.json", {"accessKey": "k"}, body
            )
            assert status == 200, status
        return time.perf_counter() - t0

    for attribution in (True, False):  # warm both paths
        one_window_s(attribution)
    samples = {True: [], False: []}
    for _ in range(reps):
        for attribution in (True, False):
            samples[attribution].append(one_window_s(attribution))
    with_hook = min(samples[True])
    without = min(samples[False])
    n_events = n_batches * batch_size
    return {
        "attribution_overhead_frac": round(
            max(0.0, (with_hook - without) / without), 5
        ),
        "batch_ingest_events_per_sec_with_hook": round(
            n_events / with_hook, 1
        ),
        "batch_ingest_events_per_sec_without_hook": round(
            n_events / without, 1
        ),
    }


def bench_quality(device_name):
    """Model-quality observability end to end: the serving window drives
    the full feedback→attribution join (queries through an engine server
    with feedback on, conversion events carrying the served prIds back
    through the event server) and reports the attributed hit-rate
    deltas off /metrics; `pio replay`'s self-replay runs as a
    zero-divergence smoke against the capture the window produced; and
    the ingest-path attribution hook is hard-gated <2% of
    /batch/events.json throughput."""
    import http.client

    from predictionio_tpu.api.engine_server import EngineServer, ServerConfig
    from predictionio_tpu.api.event_server import (
        EventServer,
        EventServerConfig,
    )
    from predictionio_tpu.data import storage as storage_mod
    from predictionio_tpu.data.event import DataMap, Event
    from predictionio_tpu.data.storage.base import (
        AccessKey,
        App,
        EngineInstance,
    )
    from predictionio_tpu.models.recommendation.engine import (
        recommendation_engine,
    )
    from predictionio_tpu.models.recommendation.evaluation import (
        _engine_params,
    )
    from predictionio_tpu.workflow import quality as quality_mod
    from predictionio_tpu.workflow.context import WorkflowContext
    from predictionio_tpu.workflow.core_workflow import CoreWorkflow
    import datetime as dt

    u, i, r = synth_ml100k()
    storage = storage_mod.memory_storage()
    app_id = storage.get_meta_data_apps().insert(App(id=0, name="default"))
    storage.get_meta_data_access_keys().insert(
        AccessKey(key="qkey", appid=app_id, events=())
    )
    events = storage.get_l_events()
    events.init(app_id)
    for uu, ii, rr in zip(
        u[:20_000].tolist(), i[:20_000].tolist(), r[:20_000].tolist()
    ):
        events.insert(
            Event(
                event="rate",
                entity_type="user",
                entity_id=f"u{uu}",
                target_entity_type="item",
                target_entity_id=f"i{ii}",
                properties=DataMap({"rating": rr}),
            ),
            app_id,
        )
    now = dt.datetime.now(dt.timezone.utc)
    CoreWorkflow.run_train(
        recommendation_engine(),
        _engine_params(rank=RANK, reg=0.05, eval_k=0),
        EngineInstance(
            id="", status="", start_time=now, end_time=now,
            engine_id="bench", engine_version="1",
            engine_variant="engine.json",
            engine_factory="predictionio_tpu.models.recommendation",
        ),
        ctx=WorkflowContext(mode="training", storage=storage),
    )
    quality_mod.get_capture().clear()
    quality_mod.get_attribution().clear()
    es = EventServer(
        storage=storage, config=EventServerConfig(port=0)
    ).start()
    server = EngineServer(
        recommendation_engine(),
        ServerConfig(
            port=0, feedback=True, access_key="qkey",
            event_server_port=es.port,
        ),
        storage=storage,
    ).start()
    try:
        scrape_before = scrape_metrics(es.port)

        def query(uid):
            conn = http.client.HTTPConnection("localhost", server.port)
            try:
                conn.request(
                    "POST", "/queries.json",
                    json.dumps({"user": f"u{uid}", "num": 5}),
                    {"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                body = json.loads(resp.read())
                assert resp.status == 200, resp.status
                return body
            finally:
                conn.close()

        n_queries = 40
        responses = [query(j % N_USERS) for j in range(n_queries)]
        served = [
            b for b in responses if b.get("prId") and b.get("itemScores")
        ]
        # the feedback predict events drain asynchronously; the
        # attribution table must see a prId before its conversion rides
        deadline = time.time() + 30
        while time.time() < deadline:
            if len(quality_mod.get_attribution()) >= len(served):
                break
            time.sleep(0.05)
        assert len(quality_mod.get_attribution()) >= len(served) > 0

        def post_event(payload):
            conn = http.client.HTTPConnection("localhost", es.port)
            try:
                conn.request(
                    "POST", "/events.json?accessKey=qkey",
                    json.dumps(payload),
                    {"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                resp.read()
                assert resp.status == 201, resp.status
            finally:
                conn.close()

        # conversions: every 2nd served prediction converts on its
        # top item; the rest emit a non-served item (outcome=miss)
        for k, body in enumerate(served):
            target = (
                body["itemScores"][0]["item"] if k % 2 == 0 else "i-none"
            )
            post_event({
                "event": "buy",
                "entityType": "user",
                "entityId": "u0",
                "targetEntityType": "item",
                "targetEntityId": target,
                "prId": body["prId"],
            })
        window = metrics_delta(
            scrape_before, scrape_metrics(es.port),
            ("pio_online_attributed_total", "pio_events_ingested_total"),
        )
        converted = sum(
            v for k, v in window.items()
            if k.startswith("pio_online_attributed_total")
            and 'outcome="converted"' in k
        )
        missed = sum(
            v for k, v in window.items()
            if k.startswith("pio_online_attributed_total")
            and 'outcome="miss"' in k
        )
        expected_converted = (len(served) + 1) // 2
        assert converted == expected_converted, (converted, window)
        hit_rate = converted / (converted + missed)

        # self-replay smoke: the capture the window just produced,
        # replayed against the SAME deployed instance, must report
        # exactly zero divergence (the pio replay determinism gate)
        records = quality_mod.get_capture().dump()
        assert len(records) >= n_queries
        replay = quality_mod.replay_capture(records, server.api.deployed)
        assert replay["diverged"] == 0, replay
        assert replay["jaccard_mean"] == 1.0, replay
        assert replay["rank_displacement_max"] == 0.0, replay

        overhead = measure_attribution_overhead()
        assert overhead["attribution_overhead_frac"] < 0.02, overhead

        emit(
            {
                "metric": "model_quality_observability",
                "value": overhead["attribution_overhead_frac"],
                "unit": "frac_ingest_overhead",
                "queries_served": n_queries,
                "attributed_hit_rate": round(hit_rate, 4),
                "attributed_converted": int(converted),
                "attributed_miss": int(missed),
                "replay_queries": replay["queries"],
                "replay_diverged": replay["diverged"],
                "replay_jaccard_mean": replay["jaccard_mean"],
                "replay_rank_displacement_max": (
                    replay["rank_displacement_max"]
                ),
                **overhead,
                "metrics_window_delta": window,
                "device": device_name,
            }
        )
    finally:
        server.shutdown()
        es.shutdown()


# --- config 2: classification NaiveBayes ---


def bench_classification(device_name):
    from predictionio_tpu.models.classification.engine import (
        NaiveBayesAlgorithm,
        NaiveBayesAlgorithmParams,
        PreparedData,
        Query,
        TrainingData,
    )

    rng = np.random.default_rng(13)
    n, F, L = 50_000, 3, 4
    # class-conditional Poisson count features (NB's native family)
    means = rng.uniform(1.0, 8.0, size=(L, F))
    labels = rng.integers(0, L, n)
    features = rng.poisson(means[labels]).astype(np.float32)
    td = TrainingData(
        labels=labels.astype(np.float32), features=features
    )
    algo = NaiveBayesAlgorithm(NaiveBayesAlgorithmParams(lambda_=1.0))
    algo.train(None, PreparedData(td=td))  # compile warm-up
    t0 = time.perf_counter()
    model = algo.train(None, PreparedData(td=td))
    train_s = time.perf_counter() - t0
    queries = [(j, Query(features=tuple(features[j]))) for j in range(2048)]
    preds = algo.batch_predict(model, queries)
    acc = float(
        np.mean([p.label == labels[j] for j, p in preds])
    )
    emit(
        {
            "metric": "nb_classification_train_wall_clock",
            "value": round(train_s, 3),
            "unit": "s",
            "vs_baseline": round(SPARK_LOCAL_NB_S / train_s, 2),
            "n_points": n,
            "train_accuracy": round(acc, 4),
            "device": device_name,
        },
        baseline_s=SPARK_LOCAL_NB_S,
    )


# --- config 3: similarproduct (cosine over ALS item factors) ---


def bench_similarproduct(device_name):
    from predictionio_tpu.models.similarproduct.engine import (
        ALSAlgorithm,
        ALSAlgorithmParams,
        Item,
        PreparedData,
        Query,
        TrainingData,
        ViewEvent,
    )

    rng = np.random.default_rng(17)
    n_users, n_items = 600, 400
    # two-group structure for a precision signal: users view within-group
    views = []
    for uu in range(n_users):
        grp = uu % 2
        lo = 0 if grp == 0 else n_items // 2
        for it in rng.choice(n_items // 2, size=30, replace=False):
            views.append(
                ViewEvent(user=f"u{uu}", item=f"i{lo + it}", t=0.0)
            )
    td = TrainingData(
        users={f"u{j}": {} for j in range(n_users)},
        items={f"i{j}": Item(categories=()) for j in range(n_items)},
        view_events=views,
    )
    algo = ALSAlgorithm(
        ALSAlgorithmParams(rank=10, num_iterations=10, lambda_=0.01, seed=3)
    )
    algo.train(None, PreparedData(td=td))  # compile warm-up
    t0 = time.perf_counter()
    model = algo.train(None, PreparedData(td=td))
    train_s = time.perf_counter() - t0
    # quality: top-5 similar items stay within the taste group
    hits = total = 0
    for probe in range(0, n_items, 37):
        res = algo.predict(model, Query(items=[f"i{probe}"], num=5))
        for s in res.item_scores:
            total += 1
            hits += (int(s.item[1:]) < n_items // 2) == (probe < n_items // 2)
    emit(
        {
            "metric": "similarproduct_train_wall_clock",
            "value": round(train_s, 3),
            "unit": "s",
            "vs_baseline": round(SPARK_LOCAL_SIMILAR_S / train_s, 2),
            "group_precision_at_5": round(hits / max(total, 1), 4),
            "device": device_name,
        },
        baseline_s=SPARK_LOCAL_SIMILAR_S,
    )


# --- config 4: e-commerce (ALS + business rules) ---


def bench_ecommerce(device_name):
    from predictionio_tpu.data import storage as storage_mod
    from predictionio_tpu.data.event import DataMap, Event
    from predictionio_tpu.data.storage.base import App
    from predictionio_tpu.models.ecommerce.engine import (
        DataSourceParams,
        DataSource,
        ECommAlgorithm,
        ECommAlgorithmParams,
        Preparator,
        Query,
    )
    from predictionio_tpu.workflow.context import WorkflowContext

    storage = storage_mod.memory_storage()
    storage_mod.set_storage(storage)
    try:
        app_id = storage.get_meta_data_apps().insert(App(id=0, name="default"))
        events = storage.get_l_events()
        events.init(app_id)
        rng = np.random.default_rng(23)
        n_users, n_items = 300, 200
        for j in range(n_items):
            events.insert(
                Event(
                    event="$set", entity_type="item", entity_id=f"i{j}",
                    properties=DataMap({"categories": ["c1"]}),
                ),
                app_id,
            )
        for uu in range(n_users):
            for it in rng.choice(n_items, size=20, replace=False):
                events.insert(
                    Event(
                        event="rate", entity_type="user", entity_id=f"u{uu}",
                        target_entity_type="item", target_entity_id=f"i{it}",
                        properties=DataMap({"rating": float(rng.integers(1, 6))}),
                    ),
                    app_id,
                )
        unavailable = [f"i{j}" for j in range(0, 40)]
        events.insert(
            Event(
                event="$set", entity_type="constraint",
                entity_id="unavailableItems",
                properties=DataMap({"items": unavailable}),
            ),
            app_id,
        )
        ctx = WorkflowContext(mode="bench", storage=storage)
        td = DataSource(DataSourceParams(app_name="default")).read_training(ctx)
        pd = Preparator().prepare(ctx, td)
        algo = ECommAlgorithm(
            ECommAlgorithmParams(rank=10, num_iterations=10, lambda_=0.05, seed=3)
        )
        algo.train(ctx, pd)  # compile warm-up
        t0 = time.perf_counter()
        model = algo.train(ctx, pd)
        train_s = time.perf_counter() - t0
        # rule compliance: no unavailable item may be recommended
        banned = set(unavailable)
        violations = checked = 0
        for uu in range(0, n_users, 11):
            res = algo.predict(model, Query(user=f"u{uu}", num=10))
            for s in res.item_scores:
                checked += 1
                violations += s.item in banned
        emit(
            {
                "metric": "ecommerce_train_wall_clock",
                "value": round(train_s, 3),
                "unit": "s",
                "vs_baseline": round(SPARK_LOCAL_ECOMM_S / train_s, 2),
                "rule_violations": violations,
                "recommendations_checked": checked,
                "device": device_name,
            },
            baseline_s=SPARK_LOCAL_ECOMM_S,
        )
    finally:
        storage_mod.set_storage(None)


# --- config 5: MetricEvaluator k-fold CV workflow ---


def bench_kfold_cv(device_name):
    from predictionio_tpu.data import storage as storage_mod
    from predictionio_tpu.data.event import DataMap, Event
    from predictionio_tpu.data.storage.base import App
    from predictionio_tpu.models.recommendation.evaluation import (
        ParamsGrid,
        RecommendationEvaluation,
    )
    from predictionio_tpu.workflow.context import WorkflowContext
    from predictionio_tpu.workflow.core_workflow import CoreWorkflow

    storage = storage_mod.memory_storage()
    app_id = storage.get_meta_data_apps().insert(App(id=0, name="default"))
    events = storage.get_l_events()
    events.init(app_id)
    rng = np.random.default_rng(29)
    # clustered preferences at a scale where each fold still trains a
    # meaningful model: 400 users x 300 items, ~40 ratings/user
    n_users, n_items = 400, 300
    for uu in range(n_users):
        grp = uu % 2
        lo = 0 if grp == 0 else n_items // 2
        for it in rng.choice(n_items // 2, size=40, replace=False):
            events.insert(
                Event(
                    event="rate", entity_type="user", entity_id=f"u{uu}",
                    target_entity_type="item",
                    target_entity_id=f"i{lo + it}",
                    properties=DataMap({"rating": float(rng.integers(3, 6))}),
                ),
                app_id,
            )
    evaluation = RecommendationEvaluation(k=10)
    grid = ParamsGrid()
    ctx = WorkflowContext(mode="evaluation", storage=storage)
    t0 = time.perf_counter()
    result = CoreWorkflow.run_evaluation(
        evaluation, grid.engine_params_list, ctx=ctx
    )
    eval_s = time.perf_counter() - t0
    emit(
        {
            "metric": "kfold_cv_eval_wall_clock",
            "value": round(eval_s, 3),
            "unit": "s",
            "vs_baseline": round(SPARK_LOCAL_CV_S / eval_s, 2),
            "grid_variants": len(result.engine_params_scores),
            "folds": 3,
            "best_precision_at_10": round(result.best_score.score, 4),
            "device": device_name,
        },
        baseline_s=SPARK_LOCAL_CV_S,
    )


# --- config 7c: compacted segment tier scan rate (sqlite) ---


def bench_segment_scan(device_name):
    """Training-scan throughput of the 1M-event sqlite ROW store before
    and after LSM-style compaction into immutable columnar segments
    (data/storage/segments.py). The row store decodes sqlite pages and
    evaluates the value rule in SQL per row; a compacted store streams
    np.frombuffer batches off mmap'd segment files through the SAME
    ``stream_columns_native`` fan-out, wire byte-identical. Headline
    ``segment_scan_events_per_sec`` (warm, page-cache-resident — the
    retrain steady state); acceptance gate is >= 2x the row-store rate.
    """
    import datetime as dt
    import shutil
    import tempfile

    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.storage import Storage
    from predictionio_tpu.data.storage.base import App
    from predictionio_tpu.data.storage.segments import CompactionPolicy
    from predictionio_tpu.models.recommendation.engine import RATING_SPEC

    n_events = int(os.environ.get("BENCH_SEGMENT_EVENTS", 1_000_000))
    n_users, n_items = 50_000, 5_000
    tmp = tempfile.mkdtemp(prefix="bench_seg_")
    try:
        storage = Storage(
            {
                "PIO_STORAGE_SOURCES_SQLITE_TYPE": "sqlite",
                "PIO_STORAGE_SOURCES_SQLITE_PATH": os.path.join(tmp, "s.db"),
                # seeding 1M rows is setup, not the measurement: big
                # committer units keep it to a handful of transactions
                "PIO_STORAGE_SOURCES_SQLITE_GROUP_COMMIT_EVENTS": "65536",
                "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQLITE",
                "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQLITE",
                "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQLITE",
            }
        )
        storage.get_meta_data_apps().insert(App(id=0, name="seg"))
        le = storage.get_l_events()
        le.init(1)
        rng = np.random.default_rng(17)
        u = rng.integers(0, n_users, n_events)
        i = rng.integers(0, n_items, n_events)
        # half-star ratings: float32-exact, so every row qualifies for
        # the columnar seal
        r = (rng.integers(1, 11, n_events) / 2.0).astype(np.float32)
        when = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
        t0 = time.perf_counter()
        chunk = 100_000
        for s in range(0, n_events, chunk):
            le.insert_batch(
                [
                    Event(
                        event="rate",
                        entity_type="user",
                        entity_id=f"u{u[j]}",
                        target_entity_type="item",
                        target_entity_id=f"i{i[j]}",
                        properties={"rating": float(r[j])},
                        event_time=when + dt.timedelta(seconds=int(j)),
                    )
                    for j in range(s, min(s + chunk, n_events))
                ],
                1,
            )
        seed_s = time.perf_counter() - t0

        scan_kwargs = dict(
            value_spec=RATING_SPEC,
            entity_type="user",
            target_entity_type="item",
            event_names=["rate", "buy"],
        )

        def scan_rate():
            t0 = time.perf_counter()
            stream = le.stream_columns_native(1, **scan_kwargs)
            total = 0
            for e, g, v in stream:
                total += len(v)
            _ = stream.names
            return total, n_events / (time.perf_counter() - t0)

        n_row, _ = scan_rate()  # warm the page cache
        assert n_row == n_events, (n_row, n_events)
        _, row_rate = scan_rate()

        t0 = time.perf_counter()
        result = le.compact_app(
            1,
            policy=CompactionPolicy(
                cold_s=0.0, min_events=1, grace_s=0.0
            ),
        )
        compact_s = time.perf_counter() - t0
        n_seg, seg_cold_rate = scan_rate()
        assert n_seg == n_events, (n_seg, n_events)
        _, seg_rate = scan_rate()
        emit(
            {
                "metric": "segment_scan_events_per_sec",
                "unit": "events/s",
                "value": round(seg_rate),
                "segment_scan_cold_events_per_sec": round(seg_cold_rate),
                "row_scan_events_per_sec": round(row_rate),
                "speedup_vs_row_store": round(seg_rate / row_rate, 2),
                "events": n_events,
                "sealed_events": result["sealed_events"],
                "segments": result["segments"],
                "compact_s": round(compact_s, 3),
                "seed_s": round(seed_s, 3),
                "device": device_name,
            }
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_delta_train(device_name):
    """Delta-training trajectory (rounds 9 + 17): retrain cost for a
    10k-event delta on the 1M-event bench store vs a full cold retrain
    of the same (grown) store. The delta round scans only rows above the
    cursor, folds them into the cached pack state, and warm-starts the
    factors from the previous model with a reduced sweep budget
    (ops/streaming); ``delta_rmse_gap`` is |RMSE(delta-trained) -
    RMSE(cold-trained)| over the full training ratings — the
    factor-quality parity gate (<= 1e-3). Acceptance:
    ``delta_retrain_s <= 0.1 * cold_retrain_s``.

    Round 17 keeps the packed wire + factor state device-resident
    between rounds (ops/streaming.ResidentPack): the measured
    steady-state round scatters only the delta rows onto the resident
    pack, so ``delta_upload_bytes`` (read from the
    ``pio_train_delta_upload_bytes`` metrics window, like
    ``resident_pack_hit`` from ``pio_resident_pack_rounds_total``) is
    proportional to the DELTA, not the store — hard gate: ≤ 10× the
    delta rows' encoded size. ``delta_retrain_resident_off_s`` is the
    same steady-state fold with residency released + disabled, the
    host-fold baseline the scatter round is judged against.
    """
    import datetime as dt
    import shutil
    import tempfile

    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.storage import Storage
    from predictionio_tpu.data.storage.base import App
    from predictionio_tpu.data.store import PEventStore
    from predictionio_tpu.models.recommendation.engine import RATING_SPEC
    from predictionio_tpu.ops.als import (
        ALSConfig,
        auto_segment_length,
        rmse,
    )
    from predictionio_tpu.ops.streaming import (
        pack_cache_clear,
        release_resident_packs,
        set_resident_training,
        train_als_streaming,
    )
    from predictionio_tpu.utils import metrics as _metrics
    from predictionio_tpu.utils.device_ledger import get_ledger

    n_events = int(os.environ.get("BENCH_DELTA_EVENTS", 1_000_000))
    n_delta = int(os.environ.get("BENCH_DELTA_DELTA_EVENTS", 10_000))
    warm_sweeps = int(os.environ.get("BENCH_DELTA_WARM_SWEEPS", 2))
    n_users, n_items = 50_000, 5_000
    tmp = tempfile.mkdtemp(prefix="bench_delta_")
    try:
        storage = Storage(
            {
                "PIO_STORAGE_SOURCES_SQLITE_TYPE": "sqlite",
                "PIO_STORAGE_SOURCES_SQLITE_PATH": os.path.join(tmp, "s.db"),
                "PIO_STORAGE_SOURCES_SQLITE_GROUP_COMMIT_EVENTS": "65536",
                "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQLITE",
                "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQLITE",
                "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQLITE",
            }
        )
        storage.get_meta_data_apps().insert(App(id=0, name="delta"))
        le = storage.get_l_events()
        le.init(1)
        rng = np.random.default_rng(23)
        when = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
        # live per-id event counts, so the steady-state rounds can craft
        # deltas the resident scatter arm accepts (see below)
        cnt_u = np.zeros(int(n_users * 1.01) + 2, np.int64)
        cnt_i = np.zeros(n_items + 2, np.int64)

        def make_events(n, t_base, u_hi, i_hi):
            u = rng.integers(0, u_hi, n)
            i = rng.integers(0, i_hi, n)
            r = (rng.integers(1, 11, n) / 2.0).astype(np.float32)
            cnt_u[: len(cnt_u)] += np.bincount(u, minlength=len(cnt_u))
            cnt_i[: len(cnt_i)] += np.bincount(i, minlength=len(cnt_i))
            return [
                Event(
                    event="rate",
                    entity_type="user",
                    entity_id=f"u{u[j]}",
                    target_entity_type="item",
                    target_entity_id=f"i{i[j]}",
                    properties={"rating": float(r[j])},
                    event_time=when + dt.timedelta(seconds=t_base + j),
                )
                for j in range(n)
            ]

        def make_existing_events(n, t_base):
            """A delta of n events on EXISTING ids whose counts stay
            clear of a segment-length multiple — the steady-state shape
            of live traffic the resident scatter arm is built for (a
            new id or a segment-boundary crossing is a designed
            fallback-to-host trigger, exercised by the warmup round)."""
            cu_nz = cnt_u[cnt_u > 0].astype(np.int32)
            ci_nz = cnt_i[cnt_i > 0].astype(np.int32)
            L_u = auto_segment_length(
                None, len(cu_nz), config.segment_length, counts=cu_nz
            )
            L_i = auto_segment_length(
                None, len(ci_nz), config.segment_length, counts=ci_nz
            )
            users = np.nonzero(cnt_u)[0]
            items = np.nonzero(cnt_i)[0]
            events = []
            ui = ii = 0
            for j in range(n):
                while cnt_u[users[ui % len(users)]] % L_u == 0:
                    ui += 1
                while cnt_i[items[ii % len(items)]] % L_i == 0:
                    ii += 1
                u = int(users[ui % len(users)])
                i = int(items[ii % len(items)])
                cnt_u[u] += 1
                cnt_i[i] += 1
                ui += 1
                ii += 1
                events.append(
                    Event(
                        event="rate",
                        entity_type="user",
                        entity_id=f"u{u}",
                        target_entity_type="item",
                        target_entity_id=f"i{i}",
                        properties={"rating": float((j % 10) + 1) / 2.0},
                        event_time=when
                        + dt.timedelta(seconds=t_base + j),
                    )
                )
            return events

        t0 = time.perf_counter()
        chunk = 100_000
        for s in range(0, n_events, chunk):
            le.insert_batch(
                make_events(
                    min(chunk, n_events - s), s, n_users, n_items
                ),
                1,
            )
        seed_s = time.perf_counter() - t0

        store = PEventStore(storage)
        scan_kwargs = dict(
            value_spec=RATING_SPEC,
            entity_type="user",
            target_entity_type="item",
            event_names=["rate", "buy"],
        )
        config = ALSConfig(rank=10, iterations=10, reg=0.05)

        # round 0: populate XLA caches AND the fold state (cursor +
        # factors) the continuous loop would carry between rounds.
        # Residency on: the cold round parks the device wire + factor
        # state under a ResidentPack, as the continuous loop would.
        pack_cache_clear()
        prev_resident = set_resident_training(True)
        t_first = {}
        train_als_streaming(
            store.stream_columns("delta", **scan_kwargs), config,
            timings=t_first,
        )

        # fold round 1 (unmeasured): first fold after a geometry change
        # pays the one-off XLA compiles for the grown shapes; the
        # continuous loop's steady state — what this config tracks — has
        # them in the jit + persistent caches. ~1% new user ids, so the
        # warm start exercises the dense-id relabel AND the resident
        # pack's fallback-to-host demotion.
        le.insert_batch(
            make_events(
                n_delta, n_events + 10, int(n_users * 1.01), n_items
            ),
            1,
        )
        t_warmup = {}
        train_als_streaming(
            store.stream_columns("delta", **scan_kwargs), config,
            timings=t_warmup, warm_sweeps=warm_sweeps,
        )
        assert t_warmup["pack_cache"] == "fold", t_warmup["pack_cache"]
        assert t_warmup.get("resident") == "fallback", t_warmup
        assert get_ledger().total_bytes(component="train-pack") == 0, (
            "fallback round must release the resident pack"
        )

        # fold round 2 (unmeasured): an existing-id delta through the
        # host fold — re-establishes residency on the grown geometry
        le.insert_batch(make_existing_events(n_delta, 2 * n_events), 1)
        t_reseat = {}
        train_als_streaming(
            store.stream_columns("delta", **scan_kwargs), config,
            timings=t_reseat, warm_sweeps=warm_sweeps,
        )
        assert t_reseat["pack_cache"] == "fold", t_reseat["pack_cache"]

        # scatter round 3 (unmeasured): first on-device delta scatter
        # pays the scatter kernels' one-off compiles
        le.insert_batch(make_existing_events(n_delta, 3 * n_events), 1)
        t_scatter0 = {}
        train_als_streaming(
            store.stream_columns("delta", **scan_kwargs), config,
            timings=t_scatter0, warm_sweeps=warm_sweeps,
        )
        assert t_scatter0.get("resident") == "scatter", t_scatter0

        # scatter round 4: the measured steady-state 10k-event delta
        # retrain, with delta_upload_bytes/resident_pack_hit read from
        # the metrics window around the round
        le.insert_batch(make_existing_events(n_delta, 4 * n_events), 1)
        reg = _metrics.get_registry()
        rounds_counter = reg.counter(
            "pio_resident_pack_rounds_total",
            "Streaming train rounds by resident-pack outcome: scatter "
            "(delta applied on device), fallback (pack demoted to the "
            "host fold), cold (no pack involved)",
            labels=("outcome",),
        )
        scatter_before = rounds_counter.labels(outcome="scatter").value
        t_delta = {}
        t0 = time.perf_counter()
        res_delta = train_als_streaming(
            store.stream_columns("delta", **scan_kwargs), config,
            timings=t_delta, warm_sweeps=warm_sweeps,
        )
        delta_retrain_s = time.perf_counter() - t0
        assert t_delta["pack_cache"] == "fold", t_delta["pack_cache"]
        assert t_delta.get("resident") == "scatter", t_delta
        resident_pack_hit = (
            rounds_counter.labels(outcome="scatter").value
            - scatter_before
        ) >= 1
        delta_upload_bytes = int(
            reg.gauge(
                "pio_train_delta_upload_bytes",
                "Host→device bytes the last streaming train round "
                "uploaded (resident scatter rounds: delta rows + "
                "touched regularizer entries only; full rounds: the "
                "whole wire + factor state)",
            ).value
        )
        resident_pack_bytes = int(
            get_ledger().total_bytes(component="train-pack")
        )
        # the delta rows' own encoded size on the wire: int32 user ids
        # + uint16 item ids + int8 half-step value codes
        delta_encoded_bytes = n_delta * (4 + 2 + 1)
        assert delta_upload_bytes <= 10 * delta_encoded_bytes, (
            f"scatter round uploaded {delta_upload_bytes} B for a "
            f"{delta_encoded_bytes} B delta — not delta-proportional"
        )

        # cold retrain of the SAME grown store (scan + pack + full
        # train), residency released + disabled so the rmse comparison
        # and the timing are the plain host pipeline
        released = release_resident_packs()
        assert released == 1, released
        assert get_ledger().total_bytes(component="train-pack") == 0
        set_resident_training(False)
        pack_cache_clear()
        t_cold = {}
        t0 = time.perf_counter()
        res_cold = train_als_streaming(
            store.stream_columns("delta", **scan_kwargs), config,
            timings=t_cold,
        )
        cold_retrain_s = time.perf_counter() - t0

        # steady-state host fold with residency still off: the
        # resident-off baseline of the same delta shape, folding off
        # the cold round's cache entry
        le.insert_batch(make_existing_events(n_delta, 5 * n_events), 1)
        t_off = {}
        t0 = time.perf_counter()
        train_als_streaming(
            store.stream_columns("delta", **scan_kwargs), config,
            timings=t_off, warm_sweeps=warm_sweeps,
        )
        delta_retrain_resident_off_s = time.perf_counter() - t0
        assert t_off["pack_cache"] == "fold", t_off["pack_cache"]
        set_resident_training(prev_resident)

        cols = store.find_columns("delta", **scan_kwargs)
        rmse_delta = rmse(
            res_delta.arrays, cols.entity_idx, cols.target_idx,
            cols.values,
        )
        rmse_cold = rmse(
            res_cold.arrays, cols.entity_idx, cols.target_idx,
            cols.values,
        )
        # convergence-telemetry overhead gate (<2% of device sweep
        # time) on a dedicated small wire, so the comparison runs the
        # same geometry with/without the telemetry executable
        overhead = measure_sweep_telemetry_overhead()
        assert overhead["sweep_telemetry_overhead_frac"] < 0.02, (
            "per-sweep telemetry overhead "
            f"{overhead['sweep_telemetry_overhead_frac']:.4f} of sweep "
            "time — the convergence instrumentation must stay noise"
        )
        emit(
            {
                "metric": "delta_retrain_s",
                "unit": "s",
                "value": round(delta_retrain_s, 3),
                "cold_retrain_s": round(cold_retrain_s, 3),
                "delta_over_cold": round(
                    delta_retrain_s / cold_retrain_s, 4
                ),
                # signed: positive = the delta-trained model is WORSE
                # than the cold one; the parity gate is <= 1e-3 (a
                # negative gap means the warm start's accumulated sweeps
                # left it better converged than a cold train)
                "delta_rmse_gap": round(rmse_delta - rmse_cold, 6),
                "rmse_delta_model": round(rmse_delta, 6),
                "rmse_cold_model": round(rmse_cold, 6),
                "delta_events": n_delta,
                "events": n_events + 5 * n_delta,
                "warm_sweeps": warm_sweeps,
                # round-17 resident-pack telemetry (metrics window
                # around the measured scatter round)
                "resident_pack_hit": bool(resident_pack_hit),
                "delta_upload_bytes": delta_upload_bytes,
                "delta_encoded_bytes": delta_encoded_bytes,
                "upload_over_encoded": round(
                    delta_upload_bytes / delta_encoded_bytes, 3
                ),
                "resident_pack_bytes": resident_pack_bytes,
                "delta_retrain_resident_off_s": round(
                    delta_retrain_resident_off_s, 3
                ),
                "delta_scan_s": round(t_delta.get("delta_scan_s", 0.0), 3),
                "fold_exposed_s": round(
                    t_delta.get("fold_exposed_s", 0.0), 3
                ),
                "delta_device_loop_s": round(
                    t_delta.get("device_loop_s", 0.0), 3
                ),
                "cold_device_loop_s": round(
                    t_cold.get("device_loop_s", 0.0), 3
                ),
                # per-sweep [user, item] factor-delta RMS: the warm
                # (2-sweep) round should land orders of magnitude below
                # the cold round's first sweeps — the convergence
                # evidence behind the reduced sweep budget
                "delta_convergence": convergence_curve(t_delta),
                "cold_convergence": convergence_curve(t_cold),
                **overhead,
                "seed_s": round(seed_s, 3),
                "device": device_name,
            }
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


# --- config: implicit-feedback training (round 19) — exact-solver
# oracle parity, iALS++ blocked-subspace speedup at equal ranking
# quality, and delta-proportional implicit scatter rounds ---


def _zipf_view_buy(rng, n_users, n_items, n_events):
    """Synthetic zipfian view/buy stream: item popularity ~ 1/(j+1),
    ~30% buys. Returns deduped (u, i, r) with the per-event-type
    confidence ratings the e-commerce DataSource assigns (view=1.0,
    buy=4.0)."""
    w = 1.0 / (1.0 + np.arange(n_items))
    w /= w.sum()
    u = rng.integers(0, n_users, n_events).astype(np.int32)
    i = rng.choice(n_items, size=n_events, p=w).astype(np.int32)
    r = np.where(rng.random(n_events) < 0.3, 4.0, 1.0).astype(np.float32)
    key = u.astype(np.int64) * n_items + i
    _, first = np.unique(key, return_index=True)
    return u[first], i[first], r[first]


def _implicit_hit_rate(model, u, i, r, n=10):
    """Mean per-user fraction of observed BUY items (r > 2) in the
    model's top-n — the ranking-quality gate for the subspace solver."""
    X = np.asarray(model.user_factors, np.float64)
    Y = np.asarray(model.item_factors, np.float64)
    scores = X @ Y.T
    buys_u, buys_i = u[r > 2], i[r > 2]
    hits = total = 0
    for uu in np.unique(buys_u):
        obs = set(buys_i[buys_u == uu].tolist())
        top = set(np.argsort(-scores[uu])[:n].tolist())
        hits += len(obs & top)
        total += min(len(obs), n)
    return hits / total


def bench_implicit_train(device_name):
    """Implicit-feedback ALS (round 19): confidence-weighted training
    on a synthetic zipfian view/buy stream. Three hard gates:

    1. ``solver=exact`` parity with the float64 host oracle
       (ops/als_reference): factor agreement within float32
       accumulation tolerance AND preference-RMSE gap < 0.01.
    2. the iALS++ blocked subspace solver (rank=64, block_size=8)
       reaches the exact solver's hit-rate@10 (within 0.01) in >= 2x
       less device solve wall-time — the per-row solve drops from
       O(k^2) to O(k^2/b + kb) gathered work per sweep.
    3. an implicit delta round still takes the resident-pack scatter
       path with ``delta_upload_bytes`` <= 10x the delta rows' encoded
       size (the wire carries raw ratings; confidences derive
       on-device, so implicit mode adds zero wire bytes).
    """
    import datetime as dt

    from predictionio_tpu.data import storage as storage_mod
    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.storage.base import App
    from predictionio_tpu.data.store import PEventStore
    from predictionio_tpu.models.recommendation.engine import RATING_SPEC
    from predictionio_tpu.ops.als import (
        ALSConfig,
        auto_segment_length,
        rmse,
        train_als,
    )
    from predictionio_tpu.ops.als_reference import (
        rmse_reference,
        train_als_reference,
    )
    from predictionio_tpu.ops.streaming import (
        pack_cache_clear,
        release_resident_packs,
        set_resident_training,
        train_als_streaming,
    )
    from predictionio_tpu.utils import metrics as _metrics
    from predictionio_tpu.utils.device_ledger import get_ledger

    rng = np.random.default_rng(5)

    # --- gate 1: exact-solver parity vs the float64 oracle (small
    # config so the O(n_users * k^3) host oracle stays fast) ---
    uo, io, ro = _zipf_view_buy(rng, 300, 120, 6_000)
    oracle_cfg = dict(rank=16, iterations=8, reg=0.05, alpha=2.0)
    m_exact_small = train_als(
        uo, io, ro, 300, 120,
        ALSConfig(
            implicit_prefs=True, seed=0, sweep_telemetry=False,
            **oracle_cfg,
        ),
    )
    Xr, Yr = train_als_reference(
        uo, io, ro, 300, 120, implicit_prefs=True, reg_mode="weighted",
        seed=0, **oracle_cfg,
    )
    factor_gap = max(
        float(np.max(np.abs(m_exact_small.user_factors - Xr))),
        float(np.max(np.abs(m_exact_small.item_factors - Yr))),
    )
    assert factor_gap < 5e-3, (
        f"implicit exact solver drifted {factor_gap} from the float64 "
        "oracle — not the same math"
    )
    ones = np.ones_like(ro)
    rmse_gap = abs(
        rmse(m_exact_small, uo, io, ones)
        - rmse_reference(Xr, Yr, uo, io, ones)
    )
    assert rmse_gap < 0.01, rmse_gap

    # --- gate 2: subspace speedup at equal ranking quality ---
    n_users = int(os.environ.get("BENCH_IMPLICIT_USERS", 4_000))
    n_items = int(os.environ.get("BENCH_IMPLICIT_ITEMS", 800))
    n_events = int(os.environ.get("BENCH_IMPLICIT_EVENTS", 120_000))
    sweeps = int(os.environ.get("BENCH_IMPLICIT_SWEEPS", 8))
    u, i, r = _zipf_view_buy(rng, n_users, n_items, n_events)
    base = dict(
        rank=64, iterations=sweeps, reg=0.05, alpha=2.0,
        implicit_prefs=True, seed=0,
    )
    cfg_exact = ALSConfig(**base)
    cfg_sub = ALSConfig(solver="subspace", block_size=8, **base)
    results = {}
    for label, cfg in (("exact", cfg_exact), ("subspace", cfg_sub)):
        t_cold = {}
        train_als(u, i, r, n_users, n_items, cfg, timings=t_cold)
        t_warm = {}  # measured pass: executables already compiled
        model = train_als(u, i, r, n_users, n_items, cfg, timings=t_warm)
        results[label] = {
            "loop_s": t_warm["device_loop_s"],
            "hit_rate": _implicit_hit_rate(model, u, i, r),
            "timings": t_warm,
        }
    exact_loop_s = results["exact"]["loop_s"]
    sub_loop_s = results["subspace"]["loop_s"]
    hr_exact = results["exact"]["hit_rate"]
    hr_sub = results["subspace"]["hit_rate"]
    solve_speedup = exact_loop_s / sub_loop_s
    assert hr_sub >= hr_exact - 0.01, (
        f"subspace hit-rate@10 {hr_sub:.4f} below exact "
        f"{hr_exact:.4f} — not equal ranking quality"
    )
    assert solve_speedup >= 2.0, (
        f"subspace solve wall-time {sub_loop_s:.3f}s vs exact "
        f"{exact_loop_s:.3f}s — {solve_speedup:.2f}x < the 2x gate"
    )

    # --- gate 3: implicit delta round stays delta-proportional over
    # the resident pack ---
    n_seed = int(os.environ.get("BENCH_IMPLICIT_SEED_EVENTS", 100_000))
    n_delta = int(os.environ.get("BENCH_IMPLICIT_DELTA_EVENTS", 2_000))
    d_users, d_items = 2_000, 400
    when = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
    cnt_u: dict = {}
    cnt_i: dict = {}

    def make_view_buy_events(n, t_base):
        uu = rng.integers(0, d_users, n)
        ii = rng.integers(0, d_items, n)
        buy = rng.random(n) < 0.3
        out = []
        for j in range(n):
            un, it = f"u{uu[j]}", f"i{ii[j]}"
            cnt_u[un] = cnt_u.get(un, 0) + 1
            cnt_i[it] = cnt_i.get(it, 0) + 1
            out.append(
                Event(
                    event="buy" if buy[j] else "view",
                    entity_type="user",
                    entity_id=un,
                    target_entity_type="item",
                    target_entity_id=it,
                    properties={"rating": 4.0 if buy[j] else 1.0},
                    event_time=when + dt.timedelta(seconds=t_base + j),
                )
            )
        return out

    d_config = ALSConfig(
        rank=16, iterations=6, reg=0.05, alpha=2.0, implicit_prefs=True,
        seed=0, solver="subspace", block_size=8,
    )

    def make_scatterable(n, t_base):
        """Existing-id deltas clear of segment boundaries (the
        steady-state live-traffic shape the scatter arm accepts)."""
        L_u = auto_segment_length(
            None, len(cnt_u), d_config.segment_length,
            counts=np.array(sorted(cnt_u.values()), np.int32),
        )
        L_i = auto_segment_length(
            None, len(cnt_i), d_config.segment_length,
            counts=np.array(sorted(cnt_i.values()), np.int32),
        )
        users, items = sorted(cnt_u), sorted(cnt_i)
        out, ui, ii = [], 0, 0
        for j in range(n):
            while cnt_u[users[ui % len(users)]] % L_u == 0:
                ui += 1
            while cnt_i[items[ii % len(items)]] % L_i == 0:
                ii += 1
            un, it = users[ui % len(users)], items[ii % len(items)]
            cnt_u[un] += 1
            cnt_i[it] += 1
            ui += 1
            ii += 1
            buy = j % 3 == 0
            out.append(
                Event(
                    event="buy" if buy else "view",
                    entity_type="user",
                    entity_id=un,
                    target_entity_type="item",
                    target_entity_id=it,
                    properties={"rating": 4.0 if buy else 1.0},
                    event_time=when + dt.timedelta(seconds=t_base + j),
                )
            )
        return out

    storage = storage_mod.memory_storage()
    storage.get_meta_data_apps().insert(App(id=0, name="impl"))
    le = storage.get_l_events()
    le.init(1)
    le.insert_batch(make_view_buy_events(n_seed, 0), 1)
    store = PEventStore(storage)
    scan_kwargs = dict(
        value_spec=RATING_SPEC,
        entity_type="user",
        target_entity_type="item",
        event_names=["view", "buy"],
    )
    pack_cache_clear()
    prev_resident = set_resident_training(True)
    try:
        t_cold = {}
        train_als_streaming(
            store.stream_columns("impl", **scan_kwargs), d_config,
            timings=t_cold,
        )
        assert t_cold.get("resident") == "cold", t_cold
        # warmup scatter round: pays the scatter kernels' compiles
        le.insert_batch(make_scatterable(n_delta, n_seed + 10), 1)
        t_s0 = {}
        train_als_streaming(
            store.stream_columns("impl", **scan_kwargs), d_config,
            timings=t_s0, warm_sweeps=2,
        )
        assert t_s0.get("resident") == "scatter", t_s0
        # measured implicit scatter round
        le.insert_batch(make_scatterable(n_delta, 2 * n_seed), 1)
        t_delta = {}
        t0 = time.perf_counter()
        train_als_streaming(
            store.stream_columns("impl", **scan_kwargs), d_config,
            timings=t_delta, warm_sweeps=2,
        )
        delta_retrain_s = time.perf_counter() - t0
        assert t_delta.get("resident") == "scatter", t_delta
        delta_upload_bytes = int(
            _metrics.get_registry().gauge(
                "pio_train_delta_upload_bytes",
                "Host→device bytes the last streaming train round "
                "uploaded (resident scatter rounds: delta rows + "
                "touched regularizer entries only; full rounds: the "
                "whole wire + factor state)",
            ).value
        )
        delta_encoded_bytes = n_delta * (4 + 2 + 1)
        assert delta_upload_bytes <= 10 * delta_encoded_bytes, (
            f"implicit scatter round uploaded {delta_upload_bytes} B "
            f"for a {delta_encoded_bytes} B delta — not "
            "delta-proportional"
        )
        released = release_resident_packs()
        assert get_ledger().total_bytes(component="train-pack") == 0
    finally:
        set_resident_training(prev_resident)
        pack_cache_clear()

    # objective trajectory of the measured subspace run (implicit-only
    # telemetry column, satellite of round 19)
    objective_curve = [
        round(row["objective"], 5)
        for row in results["subspace"]["timings"].get(
            "sweep_telemetry", []
        )
        if "objective" in row
    ]
    emit(
        {
            "metric": "implicit_train_s",
            "unit": "s",
            "value": round(sub_loop_s, 3),
            "exact_loop_s": round(exact_loop_s, 3),
            "solve_speedup": round(solve_speedup, 2),
            "hit_rate_exact": round(hr_exact, 4),
            "hit_rate_subspace": round(hr_sub, 4),
            "rank": 64,
            "block_size": 8,
            "sweeps": sweeps,
            "observations": int(len(u)),
            "oracle_factor_gap": factor_gap,
            "oracle_rmse_gap": round(rmse_gap, 6),
            "objective_curve": objective_curve,
            "delta_retrain_s": round(delta_retrain_s, 3),
            "delta_upload_bytes": delta_upload_bytes,
            "delta_encoded_bytes": delta_encoded_bytes,
            "upload_over_encoded": round(
                delta_upload_bytes / delta_encoded_bytes, 3
            ),
            "resident_packs_released": released,
            "device": device_name,
        }
    )


# --- config 12: sharded retrieval serving — parity gate, speedup, and
# the SO_REUSEPORT multi-worker saturation rig ---


def _topn_lists_match(a_items, a_scores, b_items, b_scores, tol=1e-4):
    """Exact-id parity with a tie escape hatch: the sharded and naive
    paths compute scores through different float summation shapes, so
    items whose scores sit within ``tol`` of the selection boundary may
    legally swap. Anything else is drift and fails the gate."""
    if list(a_items) == list(b_items):
        return True
    if len(a_items) != len(b_items):
        return False
    sa, sb = dict(zip(a_items, a_scores)), dict(zip(b_items, b_scores))
    boundary = min(min(a_scores, default=0.0), min(b_scores, default=0.0))
    for item in set(a_items) ^ set(b_items):
        s = sa.get(item, sb.get(item))
        if s is None or abs(s - boundary) > tol:
            return False
    for item in set(a_items) & set(b_items):
        if abs(sa[item] - sb[item]) > tol:
            return False
    return True


def _synthetic_ecomm_model(n_users, n_items, rank, seed=17):
    from predictionio_tpu.data.bimap import BiMap
    from predictionio_tpu.models.ecommerce.engine import ECommModel, Item

    rng = np.random.default_rng(seed)
    return ECommModel(
        user_factors=rng.standard_normal((n_users, rank)).astype(np.float32),
        item_factors=rng.standard_normal((n_items, rank)).astype(np.float32),
        user_index=BiMap({f"u{j}": j for j in range(n_users)}),
        item_index=BiMap({f"i{j}": j for j in range(n_items)}),
        items={
            j: Item(categories=("even",) if j % 2 == 0 else ("odd",))
            for j in range(n_items)
        },
    )


def bench_retrieval_kernel(device_name, n_items=50_000, rank=16, batch=64):
    """Part A of the saturation config: the in-process retrieval-vs-
    naive comparison on a catalog where the naive path's host
    post-filter dominates. HARD gates: byte-identical top-N ids (modulo
    float-boundary ties) on every sampled query, and >=2x speedup of
    the fused on-device path over the full-matmul + host post-filter
    path (the acceptance criterion for the build box; accelerator
    hardware is gated on qps instead, docs/PERF.md)."""
    import copy

    from predictionio_tpu.data import storage as storage_mod
    from predictionio_tpu.data.event import DataMap, Event
    from predictionio_tpu.data.storage.base import App
    from predictionio_tpu.models.ecommerce.engine import (
        ECommAlgorithm,
        ECommAlgorithmParams,
        Query,
    )

    storage = storage_mod.memory_storage()
    storage_mod.set_storage(storage)
    try:
        app_id = storage.get_meta_data_apps().insert(
            App(id=0, name="default")
        )
        events = storage.get_l_events()
        events.init(app_id)
        rng = np.random.default_rng(29)
        unavailable = [
            f"i{j}" for j in rng.choice(n_items, size=500, replace=False)
        ]
        events.insert(
            Event(
                event="$set", entity_type="constraint",
                entity_id="unavailableItems",
                properties=DataMap({"items": unavailable}),
            ),
            app_id,
        )
        model = _synthetic_ecomm_model(4096, n_items, rank)
        legacy = copy.deepcopy(model)
        algo = ECommAlgorithm(ECommAlgorithmParams(app_name="default"))
        prepped = algo.prepare_serving(None, model)
        algo.warm(prepped)

        def make_queries(seed):
            q_rng = np.random.default_rng(seed)
            out = []
            for _ in range(batch):
                uid = int(q_rng.integers(0, 4096))
                black = tuple(
                    f"i{j}"
                    for j in q_rng.choice(n_items, size=16, replace=False)
                )
                out.append(Query(user=f"u{uid}", num=10, black_list=black))
            return list(enumerate(out))

        # parity gate on a fresh sample (unavailable + blacklist masks in
        # play on every query)
        sample = make_queries(1)
        got = dict(algo.batch_predict(prepped, sample))
        want = dict(algo.batch_predict(legacy, sample))
        mismatches = [
            qi
            for qi, _ in sample
            if not _topn_lists_match(
                [s.item for s in got[qi].item_scores],
                [s.score for s in got[qi].item_scores],
                [s.item for s in want[qi].item_scores],
                [s.score for s in want[qi].item_scores],
            )
        ]
        assert not mismatches, (
            f"retrieval parity gate FAILED on {len(mismatches)}/"
            f"{len(sample)} queries (first: {mismatches[:3]}) — the fast "
            "path drifted from the naive full-matmul reference"
        )
        banned = set(unavailable)
        for qi, q in sample:
            assert all(s.item not in banned for s in got[qi].item_scores)
            assert all(
                s.item not in set(q.black_list)
                for s in got[qi].item_scores
            )

        def timed(fn, reps=5):
            fn(make_queries(99))  # warm
            best = np.inf
            for r in range(reps):
                qs = make_queries(100 + r)
                t0 = time.perf_counter()
                fn(qs)
                best = min(best, time.perf_counter() - t0)
            return best

        retr_s = timed(lambda qs: algo.batch_predict(prepped, qs))
        naive_s = timed(lambda qs: algo.batch_predict(legacy, qs))
        speedup = naive_s / retr_s
        assert speedup >= 2.0, (
            f"retrieval_vs_naive_speedup {speedup:.2f}x is below the 2x "
            f"acceptance gate (retrieval {retr_s * 1e3:.1f}ms vs naive "
            f"{naive_s * 1e3:.1f}ms per {batch}-query batch)"
        )
        return {
            "retrieval_vs_naive_speedup": round(speedup, 2),
            "retrieval_batch_ms": round(retr_s * 1e3, 2),
            "naive_batch_ms": round(naive_s * 1e3, 2),
            "retrieval_parity": "ok",
            "parity_queries": len(sample),
            "catalog_items": n_items,
        }
    finally:
        storage_mod.set_storage(None)


def bench_retrieval_quantized(
    device_name, n_items=50_000, rank=64, batch=64, n=10
):
    """The quantized arm of the saturation config (round 18): int8
    residency + two-stage retrieval + exact host refinement vs the
    exact float32 retriever on the SAME rank-64 catalog. HARD gates:

    - recall@n >= 0.999 against the exact path, over every sampled
      query batch;
    - id parity on the rescored shortlist: every id the quantized path
      returns carries the EXACT float32 score of that item (the host
      refinement rescores against the original rows, so a mismatch
      means the rescore drifted);
    - resident-bytes reduction >= 3x vs the float32 instance (the
      capacity claim, read from the same `resident_bytes` the device
      ledger registers).
    """
    from predictionio_tpu.ops.retrieval import ItemRetriever

    rng = np.random.default_rng(37)
    base = rng.standard_normal((256, rank)).astype(np.float32)
    Y = (
        base[rng.integers(0, 256, n_items)]
        + 0.3 * rng.standard_normal((n_items, rank))
    ).astype(np.float32)
    exact = ItemRetriever(Y, component="bench-exact")
    quant = ItemRetriever(Y, component="bench-quant", precision="int8")
    try:
        reduction = exact.resident_bytes / quant.resident_bytes
        assert reduction >= 3.0, (
            f"resident-bytes reduction {reduction:.2f}x is below the 3x "
            f"acceptance gate (float32 {exact.resident_bytes}B vs int8 "
            f"{quant.resident_bytes}B on the same catalog)"
        )
        hits = total = 0
        parity_fail = 0
        q_times, e_times = [], []
        for rep in range(8):
            q = rng.standard_normal((batch, rank)).astype(np.float32)
            t0 = time.perf_counter()
            es, ei = exact.topn(q, n)
            e_times.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            qs, qi = quant.topn(q, n)
            q_times.append(time.perf_counter() - t0)
            for r in range(batch):
                want = set(ei[r].tolist())
                hits += len(want & set(qi[r].tolist()))
                total += n
                # rescore parity: each returned id's score must equal
                # the exact dot product over the ORIGINAL f32 rows
                ref = Y[qi[r]] @ q[r]
                if not np.allclose(qs[r], ref, rtol=1e-5, atol=1e-5):
                    parity_fail += 1
        recall = hits / total
        assert recall >= 0.999, (
            f"quantized recall@{n} {recall:.5f} is below the 0.999 "
            "acceptance gate"
        )
        assert parity_fail == 0, (
            f"rescore id/score parity FAILED on {parity_fail} sampled "
            "queries — the exact host refinement drifted from the "
            "original factor rows"
        )
        return {
            "quantized_recall_at_n": round(recall, 5),
            "quantized_rescore_parity": "ok",
            "quantized_bytes_reduction_x": round(reduction, 2),
            "quantized_batch_ms": round(min(q_times) * 1e3, 2),
            "exact_batch_ms": round(min(e_times) * 1e3, 2),
            "quantized_bytes_per_item": round(
                quant.resident_bytes / n_items, 1
            ),
            "float32_bytes_per_item": round(
                exact.resident_bytes / n_items, 1
            ),
        }
    finally:
        exact.free()
        quant.free()


def bench_serving_saturation(device_name):
    """The round-12 acceptance rig: an SO_REUSEPORT `pio deploy
    --workers` fleet (each worker its own process, prepared serving
    state, and device slice) over shared sqlite storage, saturated by
    32 concurrent keep-alive clients. Emits `retrieval_qps` /
    `retrieval_p99_ms` with ZERO erroring queries required at peak
    load, plus the part-A kernel gates (`retrieval_vs_naive_speedup`,
    id parity) measured in-process on a 50k-item catalog."""
    import http.client
    import shutil
    import signal
    import subprocess
    import tempfile

    from predictionio_tpu.data import storage as storage_mod
    from predictionio_tpu.data.event import DataMap, Event
    from predictionio_tpu.data.storage.base import App, EngineInstance
    from predictionio_tpu.models.ecommerce.engine import (
        ECommAlgorithm,
        ECommAlgorithmParams,
        Query,
        ecommerce_engine,
    )
    from predictionio_tpu.utils.serialize import loads_model
    from predictionio_tpu.workflow.context import WorkflowContext
    from predictionio_tpu.workflow.core_workflow import CoreWorkflow
    import datetime as dt

    kernel = bench_retrieval_kernel(device_name)
    # the quantized arm: int8 residency gates (recall/rescore parity/
    # bytes reduction) on a rank-64 variant of the same catalog scale
    quantized = bench_retrieval_quantized(device_name)

    tmp = tempfile.mkdtemp(prefix="pio_saturation_")
    workers, clients, n_requests = 2, 32, 25
    port = 8199
    proc = None
    try:
        store_env = {
            "PIO_STORAGE_SOURCES_SQLITE_TYPE": "sqlite",
            "PIO_STORAGE_SOURCES_SQLITE_PATH": os.path.join(
                tmp, "storage.db"
            ),  # shared by the parent AND every fleet worker (via env)
            "PIO_STORAGE_SOURCES_LOCALFS_TYPE": "localfs",
            "PIO_STORAGE_SOURCES_LOCALFS_PATH": os.path.join(tmp, "models"),
            "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "pio_meta",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQLITE",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "pio_event",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQLITE",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "pio_model",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "LOCALFS",
        }
        storage = storage_mod.Storage(dict(store_env))
        # the in-proc naive oracle below reads the constraint entity
        # through the process-default storage — point it at the same
        # universe the fleet serves from
        storage_mod.set_storage(storage)
        app_id = storage.get_meta_data_apps().insert(
            App(id=0, name="default")
        )
        events = storage.get_l_events()
        events.init(app_id)
        rng = np.random.default_rng(31)
        n_users, n_items = 1000, 4000
        batch_ev = []
        for j in range(n_items):
            batch_ev.append(
                Event(
                    event="$set", entity_type="item", entity_id=f"i{j}",
                    properties=DataMap(
                        {"categories": ["even" if j % 2 == 0 else "odd"]}
                    ),
                )
            )
        for uu in range(n_users):
            for it in rng.choice(n_items, size=20, replace=False):
                batch_ev.append(
                    Event(
                        event="rate", entity_type="user",
                        entity_id=f"u{uu}", target_entity_type="item",
                        target_entity_id=f"i{it}",
                        properties=DataMap(
                            {"rating": float(rng.integers(1, 6))}
                        ),
                    )
                )
        unavailable = [f"i{j}" for j in range(0, 200)]
        batch_ev.append(
            Event(
                event="$set", entity_type="constraint",
                entity_id="unavailableItems",
                properties=DataMap({"items": unavailable}),
            )
        )
        for s in range(0, len(batch_ev), 500):
            events.insert_batch(batch_ev[s : s + 500], app_id)

        engine = ecommerce_engine()
        params = engine.jvalue_to_engine_params(
            {
                "datasource": {"params": {"app_name": "default"}},
                "algorithms": [
                    {
                        "name": "ecomm",
                        "params": {
                            "app_name": "default", "rank": 16,
                            "num_iterations": 5, "lambda_": 0.05,
                            "seed": 7,
                        },
                    }
                ],
            }
        )
        now = dt.datetime.now(dt.timezone.utc)
        instance_id = CoreWorkflow.run_train(
            engine,
            params,
            EngineInstance(
                id="", status="", start_time=now, end_time=now,
                engine_id="saturation", engine_version="1",
                engine_variant="engine.json",
                engine_factory=(
                    "predictionio_tpu.models.ecommerce.engine."
                    "ECommerceEngineFactory"
                ),
            ),
            ctx=WorkflowContext(mode="training", storage=storage),
        )
        assert instance_id, "training failed to persist an instance"

        variant_path = os.path.join(tmp, "engine.json")
        with open(variant_path, "w") as f:
            json.dump(
                {
                    "id": "saturation",
                    "version": "1",
                    "engineFactory": (
                        "predictionio_tpu.models.ecommerce.engine."
                        "ECommerceEngineFactory"
                    ),
                },
                f,
            )
        env = dict(os.environ)
        env.update(store_env)
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "predictionio_tpu.tools.cli",
                "deploy", "-v", variant_path,
                "--port", str(port), "--workers", str(workers),
                "--engine-instance-id", instance_id,
                "--pipeline-depth", "2", "--transport", "async",
            ],
            env=env,
        )

        def wait_ready(timeout_s=240.0):
            deadline = time.time() + timeout_s
            while time.time() < deadline:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"deploy fleet exited rc={proc.returncode}"
                    )
                try:
                    conn = http.client.HTTPConnection(
                        "localhost", port, timeout=2
                    )
                    conn.request("GET", "/status.json")
                    if conn.getresponse().status == 200:
                        conn.close()
                        return
                    conn.close()
                except OSError:
                    pass
                time.sleep(0.5)
            raise RuntimeError("fleet never became ready")

        wait_ready()

        banned = set(unavailable)

        def one_request(conn, uid):
            body = json.dumps({"user": f"u{uid}", "num": 10})
            t0 = time.perf_counter()
            conn.request(
                "POST", "/queries.json", body,
                {"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            payload = resp.read()
            ms = (time.perf_counter() - t0) * 1000
            ok = resp.status == 200
            items, scores = [], []
            if ok:
                parsed = json.loads(payload).get("itemScores", [])
                items = [s["item"] for s in parsed]
                scores = [s["score"] for s in parsed]
                ok = not (set(items) & banned)
            return ms, ok, items, scores

        def client(worker):
            conn = http.client.HTTPConnection("localhost", port)
            lat, errs = [], 0
            try:
                for j in range(n_requests):
                    ms, ok, _, _ = one_request(
                        conn, (worker * 131 + j * 7) % n_users
                    )
                    lat.append(ms)
                    errs += not ok
            finally:
                conn.close()
            return lat, errs

        client(0)  # warm every worker's serving path a little
        lat, errors = [], 0
        t0 = time.perf_counter()
        with concurrent.futures.ThreadPoolExecutor(
            max_workers=clients
        ) as pool:
            for c_lat, c_err in pool.map(client, range(clients)):
                lat.extend(c_lat)
                errors += c_err
        wall = time.perf_counter() - t0
        qps = len(lat) / wall
        assert errors == 0, (
            f"{errors} erroring/rule-violating queries at peak load — "
            "the acceptance criterion requires zero"
        )

        # HTTP-level parity gate: fleet answers (sharded on-device
        # retrieval in the workers) vs the naive host path on the SAME
        # persisted model, sampled across users
        blob = storage.get_model_data_models().get(instance_id)
        [persisted] = loads_model(blob.models)
        algo = ECommAlgorithm(
            ECommAlgorithmParams(app_name="default", rank=16)
        )
        sample_users = [int(u) for u in rng.choice(n_users, size=24)]
        naive = dict(
            algo.batch_predict(
                persisted,
                [
                    (j, Query(user=f"u{u}", num=10))
                    for j, u in enumerate(sample_users)
                ],
            )
        )
        conn = http.client.HTTPConnection("localhost", port)
        parity_fail = 0
        try:
            for j, u in enumerate(sample_users):
                _, ok, items, scores = one_request(conn, u)
                want = [s.item for s in naive[j].item_scores]
                want_s = [s.score for s in naive[j].item_scores]
                if not ok or not _topn_lists_match(
                    items, scores, want, want_s
                ):
                    parity_fail += 1
        finally:
            conn.close()
        assert parity_fail == 0, (
            f"fleet-vs-naive parity FAILED on {parity_fail}/"
            f"{len(sample_users)} sampled queries"
        )

        emit(
            {
                "metric": "retrieval_qps",
                "unit": "qps",
                "value": round(qps, 1),
                "retrieval_p50_ms": round(pctl(lat, 50), 2),
                "retrieval_p99_ms": round(pctl(lat, 99), 2),
                "workers": workers,
                "clients": clients,
                "requests": len(lat),
                "errors": errors,
                "fleet_parity_queries": len(sample_users),
                **kernel,
                **quantized,
                "device": device_name,
            }
        )
    finally:
        storage_mod.set_storage(None)
        if proc is not None and proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        shutil.rmtree(tmp, ignore_errors=True)


def bench_collector(device_name):
    """Round-15 telemetry-plane rig: an in-process collector scraping a
    REAL `pio deploy --workers 2` SO_REUSEPORT engine fleet (workers
    auto-register their sideband /metrics addresses via
    `--collector-url`) plus an event server, under sustained query
    load. Hard gates:

    - **scrape overhead < 1%**: the TARGET-side scrape cost — the wall
      time of the fleet's /metrics round trips (each worker renders +
      serves its exposition), measured DURING the load window — stays
      under 1% of the collector's poll period, so polling steals under
      1% of serving capacity. The collector-side full-sweep fraction
      (fetch + parse + span pull, `pio_collector_scrape_seconds`) is
      reported unguarded: in production the collector is its own
      process/box, and on this shared 2-core bench box its parsing
      legitimately competes with serving;
    - **stitched-trace completeness**: a sampled traced request's tree
      contains spans from >= 2 distinct PROCESSES (engine worker ->
      event server whose committer flushed the feedback write);
    - **federation exactness**: the collector's merged serving-latency
      quantiles are byte-for-byte equal to the offline union of the
      raw per-worker sideband scrapes, and zero erroring queries.
    """
    import http.client
    import shutil
    import signal
    import subprocess
    import tempfile
    import threading

    from predictionio_tpu.data import storage as storage_mod
    from predictionio_tpu.data.event import DataMap, Event
    from predictionio_tpu.data.storage.base import (
        AccessKey,
        App,
        EngineInstance,
    )
    from predictionio_tpu.tools.collector import CollectorServer
    from predictionio_tpu.utils import metrics as _m
    from predictionio_tpu.utils.telemetry import Collector
    from predictionio_tpu.workflow.context import WorkflowContext
    from predictionio_tpu.workflow.core_workflow import CoreWorkflow
    import datetime as dt

    tmp = tempfile.mkdtemp(prefix="pio_collector_")
    workers, clients, n_requests = 2, 8, 40
    port, es_port, es_side = 8299, 7299, 9299
    fleet = es_proc = None
    col = col_srv = None
    try:
        store_env = {
            "PIO_STORAGE_SOURCES_SQLITE_TYPE": "sqlite",
            "PIO_STORAGE_SOURCES_SQLITE_PATH": os.path.join(
                tmp, "storage.db"
            ),
            "PIO_STORAGE_SOURCES_LOCALFS_TYPE": "localfs",
            "PIO_STORAGE_SOURCES_LOCALFS_PATH": os.path.join(tmp, "models"),
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQLITE",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQLITE",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "LOCALFS",
        }
        storage = storage_mod.Storage(dict(store_env))
        app_id = storage.get_meta_data_apps().insert(
            App(id=0, name="default")
        )
        storage.get_meta_data_access_keys().insert(
            AccessKey(key="benchkey", appid=app_id, events=())
        )
        events = storage.get_l_events()
        events.init(app_id)
        rng = np.random.default_rng(51)
        n_users, n_items = 300, 1200
        batch_ev = []
        for uu in range(n_users):
            for it in rng.choice(n_items, size=15, replace=False):
                batch_ev.append(
                    Event(
                        event="rate", entity_type="user",
                        entity_id=f"u{uu}", target_entity_type="item",
                        target_entity_id=f"i{it}",
                        properties=DataMap(
                            {"rating": float(rng.integers(1, 6))}
                        ),
                    )
                )
        for s in range(0, len(batch_ev), 500):
            events.insert_batch(batch_ev[s : s + 500], app_id)

        from predictionio_tpu.models.recommendation import (
            RecommendationEngineFactory,
        )

        engine = RecommendationEngineFactory().apply()
        params = engine.jvalue_to_engine_params(
            {
                "datasource": {"params": {"app_name": "default"}},
                "algorithms": [
                    {
                        "name": "als",
                        "params": {
                            "rank": 8, "num_iterations": 5, "seed": 5,
                        },
                    }
                ],
            }
        )
        now = dt.datetime.now(dt.timezone.utc)
        instance_id = CoreWorkflow.run_train(
            engine,
            params,
            EngineInstance(
                id="", status="", start_time=now, end_time=now,
                engine_id="collector-bench", engine_version="1",
                engine_variant="engine.json",
                engine_factory=(
                    "predictionio_tpu.models.recommendation."
                    "RecommendationEngineFactory"
                ),
            ),
            ctx=WorkflowContext(mode="training", storage=storage),
        )
        assert instance_id, "training failed to persist an instance"
        variant_path = os.path.join(tmp, "engine.json")
        with open(variant_path, "w") as f:
            json.dump(
                {
                    "id": "collector-bench", "version": "1",
                    "engineFactory": (
                        "predictionio_tpu.models.recommendation."
                        "RecommendationEngineFactory"
                    ),
                },
                f,
            )

        # the collector first: the fleet registers itself against it
        col = Collector(
            [], poll_interval_s=2.0, access_key="benchkey"
        )
        col_srv = CollectorServer(col, port=0).start()
        col_url = f"http://localhost:{col_srv.port}"

        env = dict(os.environ)
        env.update(store_env)
        es_proc = subprocess.Popen(
            [
                sys.executable, "-m", "predictionio_tpu.tools.cli",
                "eventserver", "--port", str(es_port), "--no-compact",
                "--metrics-port", str(es_side),
            ],
            env=env,
        )
        fleet = subprocess.Popen(
            [
                sys.executable, "-m", "predictionio_tpu.tools.cli",
                "deploy", "-v", variant_path,
                "--port", str(port), "--workers", str(workers),
                "--engine-instance-id", instance_id,
                "--transport", "async",
                "--feedback", "--accesskey", "benchkey",
                "--event-server-port", str(es_port),
                "--collector-url", col_url,
            ],
            env=env,
        )

        def wait_ready(proc, p, path="/status.json", timeout_s=240.0):
            deadline = time.time() + timeout_s
            while time.time() < deadline:
                if proc.poll() is not None:
                    raise RuntimeError(f"process exited rc={proc.returncode}")
                try:
                    conn = http.client.HTTPConnection(
                        "localhost", p, timeout=2
                    )
                    conn.request("GET", path)
                    ok = conn.getresponse().status == 200
                    conn.close()
                    if ok:
                        return
                except OSError:
                    pass
                time.sleep(0.5)
            raise RuntimeError(f"port {p} never became ready")

        wait_ready(es_proc, es_port, "/")
        wait_ready(fleet, port)
        col.add_target(f"http://localhost:{es_side}")
        # the deploy supervisor auto-registers each worker's sideband;
        # wait for the registrations to land
        deadline = time.time() + 60
        while time.time() < deadline and len(col.target_urls()) < 3:
            time.sleep(0.5)
        assert len(col.target_urls()) == workers + 1, (
            "fleet workers did not auto-register with the collector: "
            f"{col.target_urls()}"
        )
        worker_targets = [
            u for u in col.target_urls()
            if u != f"http://localhost:{es_side}"
        ]

        def client(worker, n, trace_tag=None):
            conn = http.client.HTTPConnection("localhost", port)
            lat, errs = [], 0
            try:
                for j in range(n):
                    body = json.dumps(
                        {"user": f"u{(worker * 37 + j) % n_users}",
                         "num": 5}
                    )
                    headers = {"Content-Type": "application/json"}
                    if trace_tag is not None:
                        headers["X-PIO-Trace-Id"] = trace_tag
                    t0 = time.perf_counter()
                    conn.request("POST", "/queries.json", body, headers)
                    resp = conn.getresponse()
                    resp.read()
                    lat.append((time.perf_counter() - t0) * 1000)
                    errs += resp.status != 200
            finally:
                conn.close()
            return lat, errs

        def load_window():
            lat, errors = [], 0
            t0 = time.perf_counter()
            with concurrent.futures.ThreadPoolExecutor(
                max_workers=clients
            ) as pool:
                for c_lat, c_err in pool.map(
                    lambda w: client(w, n_requests), range(clients)
                ):
                    lat.extend(c_lat)
                    errors += c_err
            return lat, errors, time.perf_counter() - t0

        client(0, 5)  # warm
        base_lat, base_err, base_wall = load_window()
        qps_base = len(base_lat) / base_wall

        # window 2: identical load with the collector polling; a side
        # thread times raw /metrics round trips against every target
        # DURING the window — the target-side cost a scrape actually
        # imposes on serving
        import urllib.request as _ur

        fetch_sweeps: list = []
        stop_probe = threading.Event()

        def probe_scrape_cost():
            while not stop_probe.is_set():
                t0 = time.perf_counter()
                try:
                    for u in col.target_urls():
                        with _ur.urlopen(u + "/metrics", timeout=10) as r:
                            r.read()
                except OSError:
                    continue
                fetch_sweeps.append(time.perf_counter() - t0)
                if stop_probe.wait(0.5):
                    break

        scrape_sum_before = _m.get_registry().histogram(
            "pio_collector_scrape_seconds",
            "Wall clock of one full target scrape (metrics + health + "
            "incremental span pull)",
            buckets=_m.LATENCY_BUCKETS_S,
        ).sum
        col.start()
        probe = threading.Thread(target=probe_scrape_cost, daemon=True)
        probe.start()
        col_lat, col_err, col_wall = load_window()
        qps_col = len(col_lat) / col_wall
        # let at least one more poll land, then read the sweep cost
        time.sleep(2.5)
        stop_probe.set()
        probe.join(timeout=30)
        collector_sweep_frac = (
            _m.get_registry().histogram(
                "pio_collector_scrape_seconds",
                "Wall clock of one full target scrape (metrics + health "
                "+ incremental span pull)",
                buckets=_m.LATENCY_BUCKETS_S,
            ).sum
            - scrape_sum_before
        ) / (col_wall + 2.5)
        assert fetch_sweeps, "scrape-cost probe recorded no sweeps"
        scrape_overhead_frac = float(np.median(fetch_sweeps)) / (
            col.poll_interval_s
        )
        assert scrape_overhead_frac < 0.01, (
            f"target-side scrape cost {scrape_overhead_frac:.4f} of the "
            "poll period exceeds the 1% gate "
            f"(median sweep {float(np.median(fetch_sweeps)) * 1e3:.1f} ms "
            f"over {col.poll_interval_s:g} s)"
        )
        assert base_err == 0 and col_err == 0, (base_err, col_err)

        # stitched-trace completeness: one traced request must span >=2
        # distinct processes (engine worker -> event server committer)
        trace_id = "bench-collector-trace"
        client(0, 3, trace_tag=trace_id)
        stitched = []
        deadline = time.time() + 60
        while time.time() < deadline:
            stitched = col.stitched_spans(trace_id=trace_id)
            if len({s["instance"] for s in stitched}) >= 2:
                break
            time.sleep(0.5)
        processes = {s["instance"] for s in stitched}
        span_names = {s["name"] for s in stitched}
        assert len(processes) >= 2, (
            "stitched trace does not span two processes: "
            f"{processes} / {span_names}"
        )
        assert "predict" in span_names, span_names
        assert "group-commit-flush" in span_names, span_names

        # federation exactness: merged quantiles == offline union of
        # the raw per-worker scrapes, byte for byte
        time.sleep(1.0)
        col.stop()
        import urllib.request as _ur

        union = {}
        for u in worker_targets:
            with _ur.urlopen(u + "/metrics", timeout=10) as resp:
                for k, v in _m.parse_exposition(
                    resp.read().decode("utf-8")
                ).items():
                    union[k] = union.get(k, 0.0) + v
        col.poll_once()
        fed = _m.parse_exposition(col.render_federated())
        fam = "pio_serving_latency_seconds"
        exact = True
        for q in (0.5, 0.99):
            offline = m_quantile = None
            offline = _m.histogram_quantile_from_samples(union, fam, q)
            # restrict the federated side to the worker targets' family
            # (the event-server target carries no serving latency)
            m_quantile = _m.histogram_quantile_from_samples(fed, fam, q)
            exact = exact and (repr(offline) == repr(m_quantile))
        assert exact, "federated quantiles diverged from the offline union"

        emit(
            {
                "metric": "collector_fleet",
                "unit": "qps",
                "value": round(qps_col, 1),
                "qps_no_collector": round(qps_base, 1),
                "scrape_overhead_frac": round(scrape_overhead_frac, 5),
                "collector_sweep_frac": round(collector_sweep_frac, 5),
                "collector_targets": len(col.target_urls()),
                "stitched_processes": len(processes),
                "federation_exact": exact,
                "serving_p99_ms": round(pctl(col_lat, 99), 2),
                "errors": base_err + col_err,
                "workers": workers,
                "clients": clients,
                "device": device_name,
            }
        )
    finally:
        if col is not None:
            col.stop()
        if col_srv is not None:
            col_srv.shutdown()
        for proc in (fleet, es_proc):
            if proc is not None and proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
        shutil.rmtree(tmp, ignore_errors=True)


def bench_promotion_under_load(device_name):
    """The round-13 acceptance rig: retrain→gate→swap→drain under
    sustained query traffic, in-process (one EngineServer + the
    continuous-train loop + the promotion pipeline sharing one storage
    universe — the single-box deployment shape; the fleet shape is
    covered by tests/test_promotion.py's FleetTarget converge tests).

    Hard gates:
    - ZERO dropped/erroring queries across the whole run, including the
      swap window;
    - p99 of requests completing during the retrain+swap window bounded
      (<= max(10x the pre-swap baseline p99, 2000 ms) — the box also
      runs the retrain on its 2 cores, so the bound is generous but a
      blocking swap would blow far past it);
    - a shadow-DIVERGED candidate is refused (fleet keeps the old
      version);
    - injected faults at train_persist / persist_warm / warm_swap /
      swap_drain each leave the server on ONE consistent version, still
      serving;
    - a forced post-swap regression rolls back to the retained previous
      instance.
    """
    import datetime as dt
    import http.client
    import threading

    from predictionio_tpu.api.engine_server import (
        EngineServer,
        ServerConfig,
    )
    from predictionio_tpu.data import storage as storage_mod
    from predictionio_tpu.data.event import DataMap, Event
    from predictionio_tpu.data.storage.base import App, EngineInstance
    from predictionio_tpu.models.ecommerce.engine import ecommerce_engine
    from predictionio_tpu.workflow.context import WorkflowContext
    from predictionio_tpu.workflow.continuous import continuous_train
    from predictionio_tpu.workflow.core_workflow import CoreWorkflow
    from predictionio_tpu.workflow.promotion import (
        InProcessTarget,
        PromotionConfig,
        PromotionPipeline,
    )

    storage = storage_mod.memory_storage()
    storage_mod.set_storage(storage)
    server = None
    stop_load = threading.Event()
    try:
        app_id = storage.get_meta_data_apps().insert(
            App(id=0, name="default")
        )
        events = storage.get_l_events()
        events.init(app_id)
        rng = np.random.default_rng(13)
        n_users, n_items = 400, 1200

        def rating_events(n_per_user, t0_label):
            out = []
            for uu in range(n_users):
                for it in rng.choice(n_items, size=n_per_user, replace=False):
                    out.append(
                        Event(
                            event="rate", entity_type="user",
                            entity_id=f"u{uu}", target_entity_type="item",
                            target_entity_id=f"i{it}",
                            properties=DataMap(
                                {"rating": float(rng.integers(1, 6))}
                            ),
                        )
                    )
            return out

        batch_ev = [
            Event(
                event="$set", entity_type="item", entity_id=f"i{j}",
                properties=DataMap({"categories": ["all"]}),
            )
            for j in range(n_items)
        ] + rating_events(10, "seed")
        for s in range(0, len(batch_ev), 500):
            events.insert_batch(batch_ev[s : s + 500], app_id)

        engine = ecommerce_engine()
        params = engine.jvalue_to_engine_params(
            {
                "datasource": {"params": {"app_name": "default"}},
                "algorithms": [
                    {
                        "name": "ecomm",
                        "params": {
                            "app_name": "default", "rank": 8,
                            "num_iterations": 4, "lambda_": 0.05,
                            "seed": 7,
                        },
                    }
                ],
            }
        )

        def template():
            now = dt.datetime.now(dt.timezone.utc)
            return EngineInstance(
                id="", status="", start_time=now, end_time=now,
                engine_id="promo", engine_version="1",
                engine_variant="engine.json",
                engine_factory=(
                    "predictionio_tpu.models.ecommerce.engine."
                    "ECommerceEngineFactory"
                ),
            )

        def train_once():
            iid = CoreWorkflow.run_train(
                engine, params, template(),
                ctx=WorkflowContext(mode="training", storage=storage),
            )
            assert iid
            return iid

        v1 = train_once()
        server = EngineServer(
            engine,
            ServerConfig(port=0, batch_window_ms=1.0, capture_sample=1),
            storage=storage,
        ).start()
        port = server.port

        # --- sustained load: keep-alive clients for the whole bench ---
        clients = 6
        lat_lock = threading.Lock()
        samples = []  # (t_done, ms, ok)

        def client(worker):
            conn = http.client.HTTPConnection("localhost", port, timeout=30)
            try:
                j = 0
                while not stop_load.is_set():
                    body = json.dumps(
                        {"user": f"u{(worker * 131 + j * 7) % n_users}",
                         "num": 5}
                    )
                    t0 = time.perf_counter()
                    try:
                        conn.request(
                            "POST", "/queries.json", body,
                            {"Content-Type": "application/json"},
                        )
                        resp = conn.getresponse()
                        resp.read()
                        ok = resp.status == 200
                    except OSError:
                        conn.close()
                        conn = http.client.HTTPConnection(
                            "localhost", port, timeout=30
                        )
                        ok = False
                    ms = (time.perf_counter() - t0) * 1000
                    with lat_lock:
                        samples.append((time.perf_counter(), ms, ok))
                    j += 1
            finally:
                conn.close()

        threads = [
            threading.Thread(target=client, args=(w,), daemon=True)
            for w in range(clients)
        ]
        for t in threads:
            t.start()

        def window(t0, t1):
            with lat_lock:
                snap = list(samples)
            sel = [ms for (td, ms, ok) in snap if t0 <= td <= t1]
            errs = sum(
                1 for (td, ms, ok) in snap if t0 <= td <= t1 and not ok
            )
            return sel, errs

        # baseline window
        time.sleep(0.5)  # warm the connections
        t_base0 = time.perf_counter()
        time.sleep(2.5)
        t_base1 = time.perf_counter()
        base_lat, base_errs = window(t_base0, t_base1)
        assert base_lat, "no baseline traffic"
        p99_base = pctl(base_lat, 99)

        # --- the promoted round: delta ingest -> retrain -> gated swap
        # -> drain, all under the live load above ---
        delta = rating_events(4, "delta")
        for s in range(0, len(delta), 500):
            events.insert_batch(delta[s : s + 500], app_id)
        pipeline = PromotionPipeline(
            InProcessTarget(server),
            PromotionConfig(observe_s=1.0, observe_poll_s=0.2),
            storage=storage,
        )
        reports = []
        t_swap0 = time.perf_counter()
        # shadow_min_jaccard is domain-tuned in production; this bench's
        # synthetic uniform ratings legitimately churn ALS top-5 lists
        # between retrains (measured jaccard ~0.05), so the gate floor
        # here is loose — the refusal path is exercised explicitly with
        # a forced diverged verdict right below
        continuous_train(
            engine, params, template(), storage=storage,
            interval_s=0.01, max_rounds=1, shadow_queries=16,
            shadow_min_jaccard=0.01,
            promotion=pipeline, on_round=reports.append,
        )
        t_swap1 = time.perf_counter()
        promo = reports[-1].promotion
        assert promo and promo["outcome"] == "promoted", promo
        v2 = promo["candidate"]
        assert server.api.deployed.engine_instance.id == v2
        swap_lat, swap_errs = window(t_swap0, t_swap1)
        assert swap_lat, "no traffic during the swap window"
        p99_swap = pctl(swap_lat, 99)
        # hard gates: zero errors through the swap, bounded p99
        assert base_errs == 0 and swap_errs == 0, (
            f"dropped/erroring queries (baseline {base_errs}, "
            f"swap window {swap_errs}) — the acceptance criterion "
            "requires zero"
        )
        p99_bound = max(10 * p99_base, 2000.0)
        assert p99_swap <= p99_bound, (
            f"p99 through the swap window {p99_swap:.1f}ms exceeds the "
            f"bound {p99_bound:.1f}ms (baseline {p99_base:.1f}ms)"
        )

        # --- refusal: a shadow-diverged candidate never swaps ---
        v3 = train_once()
        rep = pipeline.promote(
            v3, shadow={"verdict": "diverged", "jaccard_mean": 0.1}
        )
        assert rep["outcome"] == "refused"
        assert server.api.deployed.engine_instance.id == v2
        refused_ok = True

        # --- fault sweep: every named stage leaves ONE consistent
        # version, still serving, zero dropped queries ---
        fault_results = {}
        for stage in (
            "train_persist", "persist_warm", "warm_swap", "swap_drain"
        ):
            def boom():
                raise RuntimeError(f"injected {stage}")

            pipeline.faults[stage] = boom
            rep = pipeline.promote(v3)
            pipeline.faults[stage] = None
            serving = server.api.deployed.engine_instance.id
            consistent = (
                rep["outcome"] == "failed"
                and rep["serving"] == serving
                and serving in (v2, v3)
            )
            fault_results[stage] = consistent
            assert consistent, (stage, rep, serving)
        assert all(fault_results.values())

        # --- forced post-swap regression -> automatic rollback ---
        before_roll = server.api.deployed.engine_instance.id
        v4 = train_once()
        roll_pipeline = PromotionPipeline(
            InProcessTarget(server),
            PromotionConfig(
                observe_s=1.0, observe_poll_s=0.2, max_error_rate=0.0
            ),
            storage=storage,
        )
        err_stop = threading.Event()

        def drive_errors():
            # the forced regression: record serving 500s through the
            # SAME transport-layer accounting a real failing handler
            # hits (api/http.record_http_error) — exactly the signal
            # the observation window watches. (The template engines
            # answer malformed queries gracefully, so a "natural" 500
            # generator doesn't exist here; tests/test_promotion.py
            # drives REAL 500s end-to-end through a failing algorithm.)
            from predictionio_tpu.api.http import record_http_error

            while not err_stop.is_set():
                record_http_error("Engine Server", "/queries.json", 500)
                err_stop.wait(0.05)

        et = threading.Thread(target=drive_errors, daemon=True)
        et.start()
        try:
            rep = roll_pipeline.promote(v4)
        finally:
            err_stop.set()
            et.join(timeout=10)
        assert rep["outcome"] == "rolled_back", rep
        assert server.api.deployed.engine_instance.id == before_roll
        rollback_ok = True

        stop_load.set()
        for t in threads:
            t.join(timeout=15)
        with lat_lock:
            total = len(samples)
        wall = time.perf_counter() - t_base0
        emit(
            {
                "metric": "promotion_under_load",
                "unit": "mixed",
                "value": round(p99_swap, 2),
                "p99_swap_window_ms": round(p99_swap, 2),
                "p99_baseline_ms": round(p99_base, 2),
                "p50_swap_window_ms": round(pctl(swap_lat, 50), 2),
                "swap_window_s": round(t_swap1 - t_swap0, 3),
                "promotion_stages_s": promo.get("stages"),
                "qps_under_load": round(total / wall, 1),
                "errors": base_errs + swap_errs,
                "shadow_refusal_enforced": refused_ok,
                "fault_stages_consistent": fault_results,
                "rollback_on_regression": rollback_ok,
                "device": device_name,
            }
        )
    finally:
        stop_load.set()
        if server is not None:
            server.shutdown()
        storage_mod.set_storage(None)


def bench_experiment(device_name):
    """The round-20 acceptance rig: the online experimentation plane
    end to end on one box, under sustained query load.

    Hard gates:
    - a 2-variant experiment where the LIVE arm is a deliberately
      degraded truncated-rank retrain loses to the candidate: the
      sequential (mSPRT) test declares the winner and the winner
      auto-promotes through the gated promotion pipeline with ZERO
      dropped/erroring queries across the whole run including the
      swap window;
    - allocation is exactly sticky: 0 cross-variant reassignments
      among all sampled users, and every observed assignment equals
      the pure allocation function;
    - an A/A run (two identically trained arms, identical conversion
      law) over the same horizon declares NO winner, and its losing
      arm's device state drains back to the pre-experiment ledger
      level (ledger-zero release);
    - the ingest-path attribution hook stays within the PR 11 <2%
      throughput gate.
    """
    import datetime as dt
    import http.client
    import threading
    import zlib

    from predictionio_tpu.api.engine_server import (
        EngineServer,
        ServerConfig,
    )
    from predictionio_tpu.data import storage as storage_mod
    from predictionio_tpu.data.event import DataMap, Event
    from predictionio_tpu.data.storage.base import App, EngineInstance
    from predictionio_tpu.models.ecommerce.engine import ecommerce_engine
    from predictionio_tpu.utils.device_ledger import get_ledger
    from predictionio_tpu.workflow import quality as quality_mod
    from predictionio_tpu.workflow.context import WorkflowContext
    from predictionio_tpu.workflow.core_workflow import CoreWorkflow
    from predictionio_tpu.workflow.experiment import (
        ExperimentRunner,
        ExperimentSpec,
        allocate,
    )
    from predictionio_tpu.workflow.promotion import (
        InProcessTarget,
        PromotionConfig,
        PromotionPipeline,
    )

    storage = storage_mod.memory_storage()
    storage_mod.set_storage(storage)
    server = None
    stop_load = threading.Event()
    try:
        app_id = storage.get_meta_data_apps().insert(
            App(id=0, name="default")
        )
        events = storage.get_l_events()
        events.init(app_id)
        rng = np.random.default_rng(20)
        n_users, n_items = 200, 600
        batch_ev = [
            Event(
                event="$set", entity_type="item", entity_id=f"i{j}",
                properties=DataMap({"categories": ["all"]}),
            )
            for j in range(n_items)
        ]
        for uu in range(n_users):
            for it in rng.choice(n_items, size=10, replace=False):
                batch_ev.append(
                    Event(
                        event="rate", entity_type="user",
                        entity_id=f"u{uu}", target_entity_type="item",
                        target_entity_id=f"i{it}",
                        properties=DataMap(
                            {"rating": float(rng.integers(1, 6))}
                        ),
                    )
                )
        for s in range(0, len(batch_ev), 500):
            events.insert_batch(batch_ev[s : s + 500], app_id)

        engine = ecommerce_engine()

        def make_params(rank, num_iterations):
            return engine.jvalue_to_engine_params(
                {
                    "datasource": {"params": {"app_name": "default"}},
                    "algorithms": [
                        {
                            "name": "ecomm",
                            "params": {
                                "app_name": "default", "rank": rank,
                                "num_iterations": num_iterations,
                                "lambda_": 0.05, "seed": 7,
                            },
                        }
                    ],
                }
            )

        def train_once(params):
            now = dt.datetime.now(dt.timezone.utc)
            iid = CoreWorkflow.run_train(
                engine, params, EngineInstance(
                    id="", status="", start_time=now, end_time=now,
                    engine_id="exp", engine_version="1",
                    engine_variant="engine.json",
                    engine_factory=(
                        "predictionio_tpu.models.ecommerce.engine."
                        "ECommerceEngineFactory"
                    ),
                ),
                ctx=WorkflowContext(mode="training", storage=storage),
            )
            assert iid
            return iid

        full = make_params(rank=8, num_iterations=4)
        v_good = train_once(full)
        # the deliberately degraded arm: truncated rank, single sweep —
        # trained LAST so a fresh server deploys it as the live control
        v_deg = train_once(make_params(rank=2, num_iterations=1))
        server = EngineServer(
            engine, ServerConfig(port=0, batch_window_ms=1.0),
            storage=storage,
        ).start()
        assert server.api.deployed.engine_instance.id == v_deg
        port = server.port

        # --- sustained sticky load + deterministic conversion law ---
        # Conversions ride the REAL attribution join (the table the
        # ingest path uses), keyed per arm: the degraded arm converts
        # at 10%, a full-rank arm at 30%; the A/A law below is keyed
        # off the user alone, so identical arms convert identically.
        attribution = quality_mod.get_attribution()
        deg_arms = {v_deg}
        lat_lock = threading.Lock()
        samples = []  # (t_done, ms, ok)
        assignments = {}  # user -> set of variants observed

        class _Conv:
            def __init__(self, pr_id, target):
                self.pr_id = pr_id
                self.target_entity_id = target

        def client(worker):
            conn = http.client.HTTPConnection("localhost", port, timeout=30)
            try:
                j = 0
                while not stop_load.is_set():
                    user = f"u{(worker * 131 + j * 7) % n_users}"
                    body = json.dumps({"user": user, "num": 5})
                    t0 = time.perf_counter()
                    ok, resp_json = False, None
                    try:
                        conn.request(
                            "POST", "/queries.json", body,
                            {"Content-Type": "application/json"},
                        )
                        resp = conn.getresponse()
                        raw = resp.read()
                        ok = resp.status == 200
                        resp_json = json.loads(raw) if ok else None
                    except OSError:
                        conn.close()
                        conn = http.client.HTTPConnection(
                            "localhost", port, timeout=30
                        )
                    ms = (time.perf_counter() - t0) * 1000
                    with lat_lock:
                        samples.append((time.perf_counter(), ms, ok))
                    if resp_json is not None:
                        variant = resp_json.get("variant")
                        if variant is not None:
                            with lat_lock:
                                assignments.setdefault(user, set()).add(
                                    variant
                                )
                        arm = variant or resp_json.get("modelVersion")
                        items = [
                            s["item"]
                            for s in resp_json.get("itemScores") or []
                        ]
                        if arm and items:
                            pr = f"pr-{worker}-{j}"
                            attribution.register(pr, arm, items)
                            rate = 10 if arm in deg_arms else 30
                            roll = zlib.crc32(
                                f"conv:{user}:{j}".encode()
                            ) % 100
                            target = items[0] if roll < rate else "i-none"
                            attribution.observe(_Conv(pr, target))
                    j += 1
            finally:
                conn.close()

        clients = 4
        threads = [
            threading.Thread(target=client, args=(w,), daemon=True)
            for w in range(clients)
        ]
        for t in threads:
            t.start()

        # --- run 1: degraded live arm vs full-rank candidate ---
        spec = ExperimentSpec(
            name="bench-deg", variants=(v_deg, v_good),
            min_samples=100, alpha=0.05, tau=0.3, horizon_s=600.0,
        )
        runner = ExperimentRunner(
            server, storage, spec,
            pipeline=PromotionPipeline(
                InProcessTarget(server),
                PromotionConfig(observe_s=0.5, observe_poll_s=0.1),
                storage=storage,
            ),
        )
        t_run0 = time.perf_counter()
        runner.start()
        final = None
        deadline = time.time() + 120
        while final is None and time.time() < deadline:
            time.sleep(0.3)
            final = runner.step()
        decision_s = time.perf_counter() - t_run0
        assert final is not None, "sequential test never decided"
        assert final["status"] == "decided", final["status"]
        assert final["winner"] == v_good, final
        assert final["resolved_winner"] == v_good
        promo = final["promotion"]
        assert promo and promo["outcome"] == "promoted", promo
        assert server.api.deployed.engine_instance.id == v_good

        # sticky allocation: 0 cross-variant reassignments, and every
        # observed assignment is exactly the pure function's answer
        with lat_lock:
            assigned = {u: set(vs) for u, vs in assignments.items()}
        reassigned = sum(1 for vs in assigned.values() if len(vs) > 1)
        assert reassigned == 0, f"{reassigned} users saw >1 variant"
        mismatches = sum(
            1
            for u, vs in assigned.items()
            if next(iter(vs)) != allocate(spec, u)
        )
        assert mismatches == 0, f"{mismatches} allocation mismatches"

        # --- run 2 (A/A): two identically trained arms, identical
        # conversion law -> NO winner at the horizon, loser drains ---
        v_aa = train_once(full)  # same params+seed as the live winner
        ledger_before = get_ledger().total_bytes()
        spec_aa = ExperimentSpec(
            name="bench-aa", variants=(v_good, v_aa),
            min_samples=50, alpha=0.05, tau=0.3, horizon_s=6.0,
        )
        runner_aa = ExperimentRunner(
            server, storage, spec_aa,
            pipeline=PromotionPipeline(
                InProcessTarget(server),
                PromotionConfig(observe_s=0.0),
                storage=storage,
            ),
        )
        runner_aa.start()
        assert get_ledger().total_bytes() > ledger_before, (
            "the A/A arm deployed no resident state to drain"
        )
        final_aa = None
        deadline = time.time() + 60
        while final_aa is None and time.time() < deadline:
            time.sleep(0.3)
            final_aa = runner_aa.step()
        assert final_aa is not None
        assert final_aa["status"] == "horizon", final_aa["status"]
        assert final_aa["winner"] is None, final_aa
        assert final_aa["resolved_winner"] == v_good  # keep-control
        assert final_aa["promotion"] is None
        assert server.api.deployed.engine_instance.id == v_good

        stop_load.set()
        for t in threads:
            t.join(timeout=15)

        # the losing A/A arm's device state drains to a ledger-zero
        # release (back to the pre-experiment residency level)
        drain_deadline = time.time() + 30
        while (
            get_ledger().total_bytes() > ledger_before
            and time.time() < drain_deadline
        ):
            time.sleep(0.1)
        ledger_after = get_ledger().total_bytes()
        assert ledger_after <= ledger_before, (
            f"loser not drained: {ledger_after} > {ledger_before} "
            "ledger bytes after release"
        )

        with lat_lock:
            total = len(samples)
            errors = sum(1 for (_, _, ok) in samples if not ok)
        assert errors == 0, (
            f"{errors} dropped/erroring queries — the acceptance "
            "criterion requires zero across the whole run"
        )

        # ingest-path attribution overhead: the PR 11 gate still holds
        # with the variant-labeled join in place
        overhead = measure_attribution_overhead()
        assert overhead["attribution_overhead_frac"] < 0.02, overhead

        emit(
            {
                "metric": "experiment_plane",
                "unit": "mixed",
                "value": round(decision_s, 2),
                "decision_s": round(decision_s, 2),
                "winner_promoted": promo["outcome"] == "promoted",
                "aa_no_winner": final_aa["winner"] is None,
                "cross_variant_reassignments": reassigned,
                "allocation_mismatches": mismatches,
                "users_sampled": len(assigned),
                "queries_total": total,
                "errors": errors,
                "loser_ledger_zero": ledger_after <= ledger_before,
                "attribution_overhead_frac": overhead[
                    "attribution_overhead_frac"
                ],
                "device": device_name,
            }
        )
    finally:
        stop_load.set()
        if server is not None:
            server.shutdown()
        storage_mod.set_storage(None)


def _spawn_gateway(port, db_path):
    """One storage-gateway NODE as a separate OS process (sqlite-backed,
    restartable on the same port + store for the kill sweep)."""
    import subprocess
    import sys

    child = (
        "import sys\n"
        "from predictionio_tpu.data.storage import Storage\n"
        "from predictionio_tpu.api.storage_gateway import "
        "StorageGatewayServer\n"
        "port, path = int(sys.argv[1]), sys.argv[2]\n"
        "cfg = {\n"
        "    'PIO_STORAGE_SOURCES_SQLITE_TYPE': 'sqlite',\n"
        "    'PIO_STORAGE_SOURCES_SQLITE_PATH': path,\n"
        "    'PIO_STORAGE_REPOSITORIES_METADATA_NAME': 'meta',\n"
        "    'PIO_STORAGE_REPOSITORIES_METADATA_SOURCE': 'SQLITE',\n"
        "    'PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME': 'event',\n"
        "    'PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE': 'SQLITE',\n"
        "    'PIO_STORAGE_REPOSITORIES_MODELDATA_NAME': 'model',\n"
        "    'PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE': 'SQLITE',\n"
        "}\n"
        "server = StorageGatewayServer(\n"
        "    Storage(cfg), ip='127.0.0.1', port=port\n"
        ")\n"
        "print('READY', server.port, flush=True)\n"
        "server.serve_forever()\n"
    )
    return subprocess.Popen(
        [sys.executable, "-c", child, str(port), str(db_path)],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def _free_ports(n):
    import socket

    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _wait_ready(port, timeout_s=90.0):
    import urllib.error
    import urllib.request

    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/readyz", timeout=2
            ) as r:
                if r.status == 200:
                    return True
        except (urllib.error.URLError, OSError):
            pass
        time.sleep(0.1)
    return False


def _cluster_storage(ports, replicas):
    from predictionio_tpu.data.storage import Storage

    return Storage(
        {
            "PIO_STORAGE_SOURCES_C_TYPE": "cluster",
            "PIO_STORAGE_SOURCES_C_NODES": ",".join(
                f"http://127.0.0.1:{p}" for p in ports
            ),
            "PIO_STORAGE_SOURCES_C_REPLICAS": str(replicas),
            "PIO_STORAGE_SOURCES_C_BREAKER_FAILURES": "2",
            "PIO_STORAGE_SOURCES_C_BREAKER_COOLDOWN_S": "0.2",
            "PIO_STORAGE_SOURCES_C_TIMEOUT_S": "20",
            "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "meta",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "C",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "event",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "C",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "model",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "C",
        }
    )


def _cluster_events(n, t_base_ms, users=97, items=53, tag="i"):
    import datetime as dt

    from predictionio_tpu.data.event import DataMap, Event

    return [
        Event(
            event="rate",
            entity_type="user",
            entity_id=f"u{j % users}",
            target_entity_type="item",
            target_entity_id=f"{tag}{j % items}",
            properties=DataMap({"rating": float(j % 5 + 1)}),
            # globally unique, increasing times: the merged wire is then
            # deterministic, so byte-identity against the single-node
            # reference is exact, not tie-dependent
            event_time=dt.datetime.fromtimestamp(
                (t_base_ms + j) / 1000.0, dt.timezone.utc
            ),
        )
        for j in range(n)
    ]


def _ingest_through_cluster(le, events, workers=4, batch=200):
    """Threaded insert_batch ingest; returns (acked list of (event,id),
    wall seconds). PartialBatchError contributes its acked slots only.

    Workers partition by USER (each entity's events ride one worker, in
    sequence): per-entity arrival order is then deterministic, which is
    the condition under which a rowid-ordered store scan is
    byte-comparable to the time-ordered reference — threads racing one
    user's batches would make per-user commit order (and thus any
    store's wire) run-dependent."""
    import threading
    import zlib

    from predictionio_tpu.data.storage.base import PartialBatchError

    lock = threading.Lock()
    acked = []

    def worker(w):
        mine = [
            ev
            for ev in events
            if zlib.crc32(ev.entity_id.encode()) % workers == w
        ]
        for s in range(0, len(mine), batch):
            chunk = mine[s : s + batch]
            try:
                ids = le.insert_batch(chunk, 1)
                failed = frozenset()
            except PartialBatchError as e:
                ids, failed = e.event_ids, e.failed_ids
            with lock:
                acked.extend(
                    (ev, eid)
                    for ev, eid in zip(chunk, ids)
                    if eid not in failed
                )

    t0 = time.perf_counter()
    threads = [
        threading.Thread(target=worker, args=(w,), daemon=True)
        for w in range(workers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return acked, time.perf_counter() - t0


def _wire_of(stream):
    from predictionio_tpu.ops import als as als_mod
    from predictionio_tpu.ops import streaming as strm

    out = strm._scan_and_pack(
        stream, als_mod.ALSConfig(rank=8, iterations=2), {}, 2
    )
    assert out is not None, "empty scan"
    return out[0]


def _model_fingerprint(wire):
    import hashlib

    from predictionio_tpu.ops import als as als_mod

    arrays = als_mod.train_from_wire(
        wire, als_mod.ALSConfig(rank=8, iterations=2, seed=11)
    )
    h = hashlib.sha256()
    h.update(np.asarray(arrays.user_factors).tobytes())
    h.update(np.asarray(arrays.item_factors).tobytes())
    return h.hexdigest()


def bench_cluster_ingest(device_name):
    """The round-14 acceptance rig (docs/STORAGE.md): multi-PROCESS
    gateway fleet behind the cluster routing backend.

    Phase 1 — scaling: threaded ingest through 1 node vs 4 nodes (R=1,
    sqlite-backed gateway processes). Hard gate: no collapse anywhere,
    and real scaling (>= 1.8x) when the box has the cores to show it —
    on a 1-2 core container every gateway process shares the client's
    core, so the recorded factor is the box's ceiling, not the tier's.

    Phase 2 — node-kill fault sweep (3 nodes, R=2): SIGKILL one gateway
    mid-ingest. Hard gates: ZERO acked-event loss; the scatter-gather
    streaming scan's merged wire stays BYTE-identical to a single-node
    store holding exactly the acked events (node down AND after
    recovery); the trained-model fingerprint is unchanged; and recovery
    completes — the restarted node's /readyz returns 200, resync
    replays its missed rows, and it rejoins the read path non-stale.
    """
    import shutil
    import tempfile

    from predictionio_tpu.data.storage import App
    from predictionio_tpu.data.storage.memory import MemLEvents

    work = tempfile.mkdtemp(prefix="pio-cluster-bench-")
    procs = []

    def spawn_fleet(n, subdir):
        ports = _free_ports(n)
        fleet = []
        for i, port in enumerate(ports):
            path = os.path.join(work, subdir, f"n{i}", "storage.db")
            os.makedirs(os.path.dirname(path), exist_ok=True)
            p = _spawn_gateway(port, path)
            procs.append(p)
            fleet.append((p, port, path))
        for _, port, _ in fleet:
            assert _wait_ready(port), f"gateway :{port} never got ready"
        return fleet

    try:
        # --- phase 1: 1 -> 4 node ingest scaling (R=1) ---
        n_events = int(os.environ.get("BENCH_CLUSTER_EVENTS", "6000"))
        rates = {}
        for n_nodes in (1, 4):
            fleet = spawn_fleet(n_nodes, f"scale{n_nodes}")
            storage = _cluster_storage(
                [port for _, port, _ in fleet], replicas=1
            )
            storage.get_meta_data_apps().insert(App(id=0, name="bench"))
            le = storage.get_l_events()
            le.init(1)
            events = _cluster_events(n_events, 1_760_000_000_000)
            acked, wall = _ingest_through_cluster(le, events)
            assert len(acked) == n_events, "events lost with no fault!"
            rates[n_nodes] = n_events / wall
            storage._client("C").close()
            for p, _, _ in fleet:
                p.kill()
        cores = os.cpu_count() or 1
        scaling = rates[4] / rates[1]
        if cores >= 4:
            assert scaling >= 1.8, (
                f"1->4 node scaling {scaling:.2f}x on a {cores}-core box "
                "— the partitioned tier must scale when the hardware can"
            )
        else:
            # every server process shares the client's core(s): gate
            # only against collapse, record the box-bound factor
            assert scaling >= 0.35, (
                f"1->4 nodes COLLAPSED to {scaling:.2f}x even on a "
                f"{cores}-core box"
            )

        # --- phase 2: node-kill fault sweep (3 nodes, R=2) ---
        fleet = spawn_fleet(3, "kill")
        storage = _cluster_storage(
            [port for _, port, _ in fleet], replicas=2
        )
        storage.get_meta_data_apps().insert(App(id=0, name="bench"))
        client = storage._client("C")
        le = storage.get_l_events()
        le.init(1)
        t_base = 1_770_000_000_000
        pre = _cluster_events(2000, t_base)
        acked1, _ = _ingest_through_cluster(le, pre)
        victim_idx = 1
        victim_proc, victim_port, victim_path = fleet[victim_idx]
        victim_proc.kill()
        victim_proc.wait(timeout=30)
        during = _cluster_events(2000, t_base + 10_000, tag="k")
        acked2, _ = _ingest_through_cluster(le, during)
        acked = acked1 + acked2
        assert len(acked2) == 2000, (
            f"{2000 - len(acked2)} events failed to ack with one "
            "replica down — quorum writes must keep acking"
        )
        assert client.nodes[victim_idx].stale, (
            "the killed node missed acked writes and must be stale"
        )

        # zero acked loss + byte-identical wire while the node is DOWN
        ref = MemLEvents()
        ref.init(1)
        ref.insert_batch(
            [ev.with_event_id(eid) for ev, eid in acked], 1
        )
        w_down = _wire_of(le.stream_columns_native(1))
        w_ref = _wire_of(ref.stream_columns_native(1))
        wire_identical_down = bool(
            np.array_equal(w_down.iw, w_ref.iw)
            and np.array_equal(w_down.vw, w_ref.vw)
        )
        assert wire_identical_down, (
            "merged wire diverged from the single-node reference with "
            "one replica killed — acked events were lost or reordered"
        )
        visible = {eid for _, eid in acked}
        scanned = {e.event_id for e in le.find(1)}
        assert visible <= scanned, (
            f"{len(visible - scanned)} ACKED events missing from the "
            "failover scatter read"
        )

        # unchanged trained-model fingerprint
        fp_down = _model_fingerprint(w_down)
        fp_ref = _model_fingerprint(w_ref)
        assert fp_down == fp_ref, "trained-model fingerprint changed"

        # --- recovery: restart on the same port + store, resync ---
        p2 = _spawn_gateway(victim_port, victim_path)
        procs.append(p2)
        assert _wait_ready(victim_port), "restarted node never ready"
        report = client.resync()
        label = client.nodes[victim_idx].label
        assert "resynced" in report["nodes"].get(label, ""), report
        assert not client.nodes[victim_idx].stale
        assert client.nodes[victim_idx].available()
        w_back = _wire_of(le.stream_columns_native(1))
        wire_identical_recovered = bool(
            np.array_equal(w_back.iw, w_ref.iw)
            and np.array_equal(w_back.vw, w_ref.vw)
        )
        assert wire_identical_recovered, (
            "wire diverged after the node rejoined — resync replayed "
            "the wrong rows"
        )
        client.close()
        emit(
            {
                "metric": "cluster_ingest",
                "unit": "events/s",
                "value": round(rates[4], 1),
                "events_per_sec_1node": round(rates[1], 1),
                "events_per_sec_4node": round(rates[4], 1),
                "scaling_4_over_1": round(scaling, 3),
                "cores": cores,
                "cpu_bound": cores < 4,
                "replicas": 2,
                "acked_during_kill": len(acked2),
                "acked_events_lost": 0,
                "wire_identical_node_down": wire_identical_down,
                "wire_identical_recovered": wire_identical_recovered,
                "model_fingerprint_unchanged": fp_down == fp_ref,
                "resynced_events": report["events"],
                "device": device_name,
            }
        )
    finally:
        for p in procs:
            try:
                p.kill()
            except OSError:
                pass
        shutil.rmtree(work, ignore_errors=True)


def bench_device_obs(device_name):
    """Round-16 device-observability acceptance rig (in-process
    recommendation server, real ALS model):

    Hard gates:
    - ledger + efficiency-metric overhead <1% of the serving p50: the
      per-batch instrumentation the device plane added to the hot path
      (padding-waste gauge set, executable-cache seen-key check, ledger
      gauge publish at registration cadence) is timed directly and
      compared against the measured REST p50;
    - profile-capture smoke: a POST /debug/profile capture taken while
      concurrent clients hammer /queries.json returns a non-empty
      jax.profiler archive with ZERO dropped/erroring queries during
      the window;
    - ledger lifecycle: resident bytes nonzero while deployed, zero
      after shutdown (the release invariant, fleet-visible).
    """
    import base64
    import datetime as dt
    import http.client
    import threading

    from predictionio_tpu.api.engine_server import (
        EngineServer,
        ServerConfig,
    )
    from predictionio_tpu.data import storage as storage_mod
    from predictionio_tpu.data.event import DataMap, Event
    from predictionio_tpu.data.storage.base import App, EngineInstance
    from predictionio_tpu.models.recommendation.engine import (
        recommendation_engine,
    )
    from predictionio_tpu.models.recommendation.evaluation import (
        _engine_params,
    )
    from predictionio_tpu.utils import compilation_cache as cc_mod
    from predictionio_tpu.utils import device_ledger as dl
    from predictionio_tpu.utils import metrics as metrics_mod
    from predictionio_tpu.workflow.context import WorkflowContext
    from predictionio_tpu.workflow.core_workflow import CoreWorkflow

    rng = np.random.default_rng(16)
    n_users, n_items, n_ratings = 300, 600, 9000
    u = rng.integers(0, n_users, n_ratings)
    i = rng.integers(0, n_items, n_ratings)
    r = rng.integers(1, 6, n_ratings).astype(np.float32)

    storage = storage_mod.memory_storage()
    app_id = storage.get_meta_data_apps().insert(App(id=0, name="default"))
    events = storage.get_l_events()
    events.init(app_id)
    batch = [
        Event(
            event="rate", entity_type="user", entity_id=f"u{uu}",
            target_entity_type="item", target_entity_id=f"i{ii}",
            properties=DataMap({"rating": float(rr)}),
        )
        for uu, ii, rr in zip(u.tolist(), i.tolist(), r.tolist())
    ]
    for s in range(0, len(batch), 1000):
        events.insert_batch(batch[s : s + 1000], app_id)

    now = dt.datetime.now(dt.timezone.utc)
    CoreWorkflow.run_train(
        recommendation_engine(),
        _engine_params(rank=8, reg=0.05, eval_k=0),
        EngineInstance(
            id="", status="", start_time=now, end_time=now,
            engine_id="devobs", engine_version="1",
            engine_variant="engine.json",
            engine_factory="predictionio_tpu.models.recommendation",
        ),
        ctx=WorkflowContext(mode="training", storage=storage),
    )
    server = EngineServer(
        recommendation_engine(),
        ServerConfig(
            port=0, batch_window_ms=1.0, pipeline_depth=2,
            access_key="bench-secret",
        ),
        storage=storage,
    ).start()
    try:
        ledger_mb = dl.get_ledger().total_bytes() / 2**20

        def one_request(conn, uid):
            body = json.dumps({"user": f"u{uid}", "num": 10})
            t0 = time.perf_counter()
            conn.request(
                "POST", "/queries.json", body,
                {"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            resp.read()
            assert resp.status == 200, resp.status
            return (time.perf_counter() - t0) * 1000

        conn = http.client.HTTPConnection("localhost", server.port)
        try:
            for j in range(10):  # warm every executable on the path
                one_request(conn, j)
            lat = [one_request(conn, j % n_users) for j in range(200)]
        finally:
            conn.close()
        p50_ms = pctl(lat, 50)

        # --- the instrumentation the device plane ADDED to one served
        # batch: padding-waste gauge set + executable seen-key check
        # (warm path) + mask-age gauge set; measured directly ---
        gauge = metrics_mod.get_registry().gauge(
            "pio_padding_waste_ratio",
            "Fraction of a padded dimension that is padding (0 = no "
            "waste): serving batch rows, top-k ladder width, ALS "
            "geometry-bucket slots — the compile-sharing cost the "
            "capacity planning reads",
            labels=("site",),
        ).labels(site="retrieval_batch")
        seen = {("k", 8, True)}
        reps = 20000
        t0 = time.perf_counter()
        for _ in range(reps):
            gauge.set(0.5)
            with cc_mod.track_compile("bench-warm", seen, ("k", 8, True)):
                pass
        instr_ms_per_batch = (time.perf_counter() - t0) / reps * 1000
        instr_overhead_frac = instr_ms_per_batch / max(p50_ms, 1e-9)
        assert instr_overhead_frac < 0.01, (
            f"device-plane instrumentation {instr_ms_per_batch:.4f}ms "
            f"per batch is {instr_overhead_frac:.2%} of the "
            f"{p50_ms:.2f}ms serving p50 (gate: <1%)"
        )

        # --- profile capture under load: non-empty archive, zero
        # erroring queries during the window ---
        errors = []
        stop = threading.Event()

        def load(worker):
            conn = http.client.HTTPConnection("localhost", server.port)
            try:
                j = 0
                while not stop.is_set():
                    try:
                        one_request(conn, (worker * 17 + j) % n_users)
                    except AssertionError as e:
                        errors.append(str(e))
                    j += 1
            finally:
                conn.close()

        threads = [
            threading.Thread(target=load, args=(w,), daemon=True)
            for w in range(4)
        ]
        for t in threads:
            t.start()
        capture_s = 1.0
        try:
            conn = http.client.HTTPConnection(
                "localhost", server.port, timeout=60
            )
            try:
                conn.request(
                    "POST",
                    f"/debug/profile?seconds={capture_s}"
                    "&accessKey=bench-secret",
                    b"",
                )
                resp = conn.getresponse()
                assert resp.status == 200, resp.status
                payload = json.loads(resp.read())
            finally:
                conn.close()
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
        archive = base64.b64decode(payload["archive_b64"])
        assert len(archive) > 0 and payload["files"], (
            "profile capture produced an empty archive"
        )
        assert not errors, (
            f"{len(errors)} serving errors during the capture window"
        )
        scrape = scrape_metrics(server.port)
        from predictionio_tpu.utils.metrics import counter_sum

        hbm_bytes = counter_sum(scrape, "pio_device_ledger_bytes")
        assert hbm_bytes > 0, "no ledger residency visible on /metrics"
    finally:
        server.shutdown()
    ledger_after = dl.get_ledger().total_bytes(component="serving-factors")
    assert ledger_after == 0, (
        f"{ledger_after} serving-factors bytes still registered after "
        "server release — the ledger release invariant failed"
    )
    emit(
        {
            "metric": "device_obs",
            "unit": "overhead_frac",
            "value": round(instr_overhead_frac, 6),
            "serving_p50_ms": round(p50_ms, 3),
            "instr_ms_per_batch": round(instr_ms_per_batch, 5),
            "profile_archive_bytes": len(archive),
            "profile_capture_s": capture_s,
            "profile_trace_files": len(payload["files"]),
            "errors_during_capture": len(errors),
            "ledger_resident_mb": round(ledger_mb, 3),
            "ledger_bytes_after_release": int(ledger_after),
            "device": device_name,
        }
    )


BENCHES = {
    "recommendation": bench_recommendation,
    "classification": bench_classification,
    "similarproduct": bench_similarproduct,
    "ecommerce": bench_ecommerce,
    "kfold_cv": bench_kfold_cv,
    "ml20m": bench_ml20m,
    "ml20m_store": bench_ml20m_store,
    "ingestion": bench_ingestion,
    "concurrent_ingest": bench_concurrent_ingest,
    "quality": bench_quality,
    "segment_scan": bench_segment_scan,
    "delta_train": bench_delta_train,
    "implicit_train": bench_implicit_train,
    "serving_saturation": bench_serving_saturation,
    "promotion_under_load": bench_promotion_under_load,
    "experiment": bench_experiment,
    "cluster_ingest": bench_cluster_ingest,
    "collector": bench_collector,
    "device_obs": bench_device_obs,
}


def main(argv=None):
    import argparse

    import jax

    from predictionio_tpu.utils.compilation_cache import (
        ensure_compilation_cache,
    )

    # the persistent XLA cache turns every re-bench (and the next
    # process's first train/deploy) into a warm start — without it each
    # fresh run pays ~10 s of compiles on the ML-20M shapes alone
    ensure_compilation_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--only",
        choices=sorted(BENCHES),
        action="append",
        help="run only the named config(s); default runs all, headline first",
    )
    ap.add_argument(
        "--trace-loop",
        action="store_true",
        help="capture a jax.profiler trace of the ML-20M device loop and "
        "write the per-op attribution table to docs/ALS_LOOP_TRACE.json "
        "(run on TPU hardware; honors BENCH_ML20M_* env knobs)",
    )
    args = ap.parse_args(argv)
    device_name = str(jax.devices()[0])
    if args.trace_loop:
        trace_als_loop(device_name)
        return
    names = args.only or list(BENCHES)
    for name in names:
        BENCHES[name](device_name)
    emit_summary()


if __name__ == "__main__":
    main()
