"""Unified metrics registry + Prometheus-text exposition.

The observability tentpole (SURVEY.md §5 plans "per-phase timers as
first-class"; the reference's only serving stats are the coarse
request-count bookkeeping in CreateServer.scala:399-404). Every server
in this package — event server, engine server, storage gateway — and
every background subsystem (group-commit committers, the segment
compactor, the pack cache, continuous training) records into ONE
process-global registry, exposed as Prometheus text at ``GET /metrics``
on each server. ``status.json`` keys that used to be N private
lock-guarded tallies are now reads of the same registry.

Three instrument kinds:

- :class:`Counter` — monotonically increasing float, with labels;
- :class:`Gauge` — settable float, with labels;
- :class:`Histogram` — **mergeable** fixed-bucket histogram. Bounds are
  fixed at family creation (log-spaced by default), so two workers of an
  SO_REUSEPORT fleet produce bucket vectors that ADD: the merged p99
  equals the p99 a single combined worker would have estimated. The
  512-sample reservoir this replaces structurally could not merge
  (concatenating reservoirs biases toward whichever worker sampled
  less traffic).

Hot-path cost: one dict lookup + one per-child ``threading.Lock``
acquire per record. There is no registry-global lock on the record
path (the registry lock only guards family/child CREATION), so serving
instrumentation adds no shared contention point beyond what each
instrument's own callers already serialize on — strictly less sharing
than the single ``_stats_lock`` the engine server used for everything.

Per-instance views over process-global instruments: a server that wants
"since I started" numbers (status.json) takes a :meth:`Counter.snapshot`
/ :meth:`Histogram.snapshot` at construction and reads deltas against
it; ``/metrics`` always reports process-lifetime values.
"""

from __future__ import annotations

import bisect
import math
import re
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "HistogramSnapshot",
    "MetricsRegistry",
    "get_registry",
    "log_buckets",
    "quantile_from_buckets",
    "merge_snapshots",
    "parse_exposition",
    "parse_exposition_families",
    "parse_labels",
    "sample_family_name",
    "sample_label_value",
    "counter_sum",
    "gauge_max",
    "histogram_quantile_from_samples",
    "render_content_type",
    "LATENCY_BUCKETS_S",
    "BATCH_SIZE_BUCKETS",
    "ROW_COUNT_BUCKETS",
    "CONVERGENCE_BUCKETS",
]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def log_buckets(lo: float, hi: float, factor: float = 2.0) -> Tuple[float, ...]:
    """Fixed log-spaced bucket bounds from ``lo`` up to at least ``hi``.

    Fixed (config-independent) bounds are what makes histograms
    mergeable across processes: every worker slices the axis the same
    way, so bucket vectors add element-wise.
    """
    if lo <= 0 or hi <= lo or factor <= 1.0:
        raise ValueError("need 0 < lo < hi and factor > 1")
    out: List[float] = []
    v = float(lo)
    while True:
        out.append(v)
        if v >= hi * (1 - 1e-12):  # last finite bound covers hi
            break
        v *= factor
    return tuple(out)


# serving/RPC latency in seconds: 100 µs .. ~105 s, ×2 per bucket
LATENCY_BUCKETS_S = log_buckets(1e-4, 100.0)
# micro-batch fill / REST batch sizes: 1 .. 1024, ×2
BATCH_SIZE_BUCKETS = log_buckets(1.0, 1024.0)
# group-commit flush rows / sealed-row counts: 1 .. 65536, ×4
ROW_COUNT_BUCKETS = log_buckets(1.0, 65536.0, 4.0)
# per-sweep ALS factor-delta RMS (convergence telemetry): spans the
# warm-start tail (~1e-6) through a cold first sweep (~1), ×2
CONVERGENCE_BUCKETS = log_buckets(1e-6, 4.0)


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v: float) -> str:
    """Prometheus number formatting: integers bare, floats as repr."""
    if v == math.inf:
        return "+Inf"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(float(v))


def _labels_key(
    label_names: Tuple[str, ...], kv: Dict[str, str]
) -> Tuple[str, ...]:
    if set(kv) != set(label_names):
        raise ValueError(
            f"labels {sorted(kv)} do not match declared {list(label_names)}"
        )
    return tuple(str(kv[name]) for name in label_names)


def _render_labels(
    label_names: Tuple[str, ...], values: Tuple[str, ...],
    extra: Optional[Tuple[str, str]] = None,
) -> str:
    pairs = [
        f'{n}="{_escape_label_value(v)}"'
        for n, v in zip(label_names, values)
    ]
    if extra is not None:
        pairs.append(f'{extra[0]}="{_escape_label_value(extra[1])}"')
    return "{" + ",".join(pairs) + "}" if pairs else ""


class _Family:
    """One metric family: a name, a type, declared label names, and the
    per-labelset children. Child creation is guarded by the registry
    lock; the record path touches only the child's own lock."""

    kind = "untyped"

    def __init__(self, name: str, help: str, label_names: Sequence[str]):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in label_names:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._children: Dict[Tuple[str, ...], object] = {}
        self._lock = threading.Lock()

    def _make_child(self):
        raise NotImplementedError

    def labels(self, **kv) -> object:
        key = _labels_key(self.label_names, kv)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = self._make_child()
                    self._children[key] = child
        return child

    def _default(self):
        """The label-less child (for families declared without labels)."""
        if self.label_names:
            raise ValueError(
                f"{self.name} declares labels {self.label_names}; "
                "use .labels(...)"
            )
        return self.labels()

    def children(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())

    def reset(self) -> None:
        """Zero every child (tests / explicit cache-clear semantics)."""
        with self._lock:
            for child in self._children.values():
                child._reset()  # type: ignore[attr-defined]

    def render(self) -> List[str]:
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} {self.kind}",
        ]
        for values, child in self.children():
            lines.extend(child._render(self, values))  # type: ignore
        return lines


class _CounterValue:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up; use a gauge")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> float:
        return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0.0

    def _render(self, family: "_Family", values: Tuple[str, ...]) -> List[str]:
        return [
            f"{family.name}"
            f"{_render_labels(family.label_names, values)} "
            f"{_fmt(self._value)}"
        ]


class _GaugeValue(_CounterValue):
    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:  # gauges may go down
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)


class Counter(_Family):
    kind = "counter"

    def _make_child(self) -> _CounterValue:
        return _CounterValue()

    # label-less convenience: family doubles as its single child
    def inc(self, n: float = 1.0) -> None:
        self._default().inc(n)

    @property
    def value(self) -> float:
        return self._default().value

    def snapshot(self) -> float:
        return self._default().snapshot()


class Gauge(_Family):
    kind = "gauge"

    def _make_child(self) -> _GaugeValue:
        return _GaugeValue()

    def set(self, v: float) -> None:
        self._default().set(v)

    def inc(self, n: float = 1.0) -> None:
        self._default().inc(n)

    def dec(self, n: float = 1.0) -> None:
        self._default().dec(n)

    @property
    def value(self) -> float:
        return self._default().value


class HistogramSnapshot:
    """An immutable (bounds, bucket counts, sum, count) capture —
    the unit of merging and of per-instance delta views."""

    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(
        self,
        bounds: Tuple[float, ...],
        counts: Tuple[int, ...],
        total: float,
        count: int,
    ):
        self.bounds = bounds
        self.counts = counts
        self.sum = total
        self.count = count

    def quantile(self, q: float) -> float:
        return quantile_from_buckets(self.bounds, self.counts, q)

    def merge(self, other: "HistogramSnapshot") -> "HistogramSnapshot":
        return merge_snapshots([self, other])

    def delta(self, base: "HistogramSnapshot") -> "HistogramSnapshot":
        """This snapshot minus an earlier one of the same family — the
        per-instance "since construction" view status.json uses."""
        if base.bounds != self.bounds:
            raise ValueError("snapshot bounds differ; cannot delta")
        return HistogramSnapshot(
            self.bounds,
            tuple(a - b for a, b in zip(self.counts, base.counts)),
            self.sum - base.sum,
            self.count - base.count,
        )


def merge_snapshots(snaps: Iterable[HistogramSnapshot]) -> HistogramSnapshot:
    """Merge same-bounds histograms by adding bucket vectors — the
    SO_REUSEPORT worker-fleet aggregation path. Because the bounds are
    fixed, the merged quantile estimate is IDENTICAL to what one worker
    observing the union of samples would report."""
    snaps = list(snaps)
    if not snaps:
        raise ValueError("nothing to merge")
    bounds = snaps[0].bounds
    for s in snaps[1:]:
        if s.bounds != bounds:
            raise ValueError("histogram bounds differ; cannot merge")
    counts = [0] * len(snaps[0].counts)  # finite buckets + the +Inf slot
    total = 0.0
    count = 0
    for s in snaps:
        for i, c in enumerate(s.counts):
            counts[i] += c
        total += s.sum
        count += s.count
    return HistogramSnapshot(bounds, tuple(counts), total, count)


def quantile_from_buckets(
    bounds: Sequence[float], counts: Sequence[int], q: float
) -> float:
    """Bucket-interpolated quantile: find the bucket holding rank
    ``q * count`` and linearly interpolate inside it (the standard
    Prometheus ``histogram_quantile`` estimator). The +Inf overflow
    bucket clamps to the highest finite bound."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        if cum + c >= rank:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i] if i < len(bounds) else bounds[-1]
            if hi == math.inf or i >= len(bounds):
                return float(bounds[-1])
            frac = (rank - cum) / c
            return float(lo + (hi - lo) * max(0.0, min(1.0, frac)))
        cum += c
    return float(bounds[-1])


class _HistogramValue:
    __slots__ = ("_bounds", "_counts", "_sum", "_count", "_lock")

    def __init__(self, bounds: Tuple[float, ...]):
        self._bounds = bounds
        # one slot per finite bound + one +Inf overflow slot
        self._counts = [0] * (len(bounds) + 1)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        i = bisect.bisect_left(self._bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def count(self) -> int:
        return self._count

    def snapshot(self) -> HistogramSnapshot:
        with self._lock:
            return HistogramSnapshot(
                self._bounds, tuple(self._counts), self._sum, self._count
            )

    def quantile(
        self, q: float, since: Optional[HistogramSnapshot] = None
    ) -> float:
        snap = self.snapshot()
        if since is not None:
            snap = snap.delta(since)
        return snap.quantile(q)

    def _reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self._bounds) + 1)
            self._sum = 0.0
            self._count = 0

    def _render(self, family: "_Family", values: Tuple[str, ...]) -> List[str]:
        with self._lock:
            counts = list(self._counts)
            total, count = self._sum, self._count
        lines = []
        cum = 0
        for bound, c in zip(self._bounds, counts):
            cum += c
            lines.append(
                f"{family.name}_bucket"
                f"{_render_labels(family.label_names, values, ('le', _fmt(bound)))} "
                f"{cum}"
            )
        cum += counts[-1]
        lines.append(
            f"{family.name}_bucket"
            f"{_render_labels(family.label_names, values, ('le', '+Inf'))} "
            f"{cum}"
        )
        labels = _render_labels(family.label_names, values)
        lines.append(f"{family.name}_sum{labels} {_fmt(total)}")
        lines.append(f"{family.name}_count{labels} {count}")
        return lines


class Histogram(_Family):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        label_names: Sequence[str],
        buckets: Sequence[float],
    ):
        super().__init__(name, help, label_names)
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.bounds = bounds

    def _make_child(self) -> _HistogramValue:
        return _HistogramValue(self.bounds)

    def observe(self, v: float) -> None:
        self._default().observe(v)

    def snapshot(self) -> HistogramSnapshot:
        return self._default().snapshot()

    def quantile(
        self, q: float, since: Optional[HistogramSnapshot] = None
    ) -> float:
        return self._default().quantile(q, since)

    @property
    def sum(self) -> float:
        return self._default().sum

    @property
    def count(self) -> int:
        return self._default().count


class MetricsRegistry:
    """Thread-safe family registry. Families are get-or-create by name
    (two servers in one process share the family); re-registering a name
    with a different kind/labels/buckets raises — a silent mismatch
    would corrupt the exposition."""

    def __init__(self):
        self._families: Dict[str, _Family] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, name: str, factory, kind: str, check) -> _Family:
        fam = self._families.get(name)
        if fam is None:
            with self._lock:
                fam = self._families.get(name)
                if fam is None:
                    fam = factory()
                    self._families[name] = fam
                    return fam
        if fam.kind != kind:
            raise ValueError(
                f"metric {name!r} already registered as {fam.kind}, "
                f"not {kind}"
            )
        check(fam)
        return fam

    def counter(
        self, name: str, help: str, labels: Sequence[str] = ()
    ) -> Counter:
        def check(fam):
            if fam.label_names != tuple(labels):
                raise ValueError(
                    f"metric {name!r} label mismatch: "
                    f"{fam.label_names} vs {tuple(labels)}"
                )

        return self._get_or_create(  # type: ignore[return-value]
            name, lambda: Counter(name, help, labels), "counter", check
        )

    def gauge(self, name: str, help: str, labels: Sequence[str] = ()) -> Gauge:
        def check(fam):
            if fam.label_names != tuple(labels):
                raise ValueError(
                    f"metric {name!r} label mismatch: "
                    f"{fam.label_names} vs {tuple(labels)}"
                )

        return self._get_or_create(  # type: ignore[return-value]
            name, lambda: Gauge(name, help, labels), "gauge", check
        )

    def histogram(
        self,
        name: str,
        help: str,
        labels: Sequence[str] = (),
        buckets: Sequence[float] = LATENCY_BUCKETS_S,
    ) -> Histogram:
        def check(fam):
            if fam.label_names != tuple(labels):
                raise ValueError(
                    f"metric {name!r} label mismatch: "
                    f"{fam.label_names} vs {tuple(labels)}"
                )
            if fam.bounds != tuple(sorted(float(b) for b in buckets)):
                raise ValueError(f"metric {name!r} bucket-bound mismatch")

        return self._get_or_create(  # type: ignore[return-value]
            name,
            lambda: Histogram(name, help, labels, buckets),
            "histogram",
            check,
        )

    def families(self) -> List[_Family]:
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4: one ``# HELP`` and
        one ``# TYPE`` line per family, then the samples."""
        lines: List[str] = []
        for fam in self.families():
            lines.extend(fam.render())
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Zero every instrument (tests only; a live scrape target must
        never reset its counters)."""
        for fam in self.families():
            fam.reset()


def render_content_type() -> str:
    """The Prometheus text exposition content type, fully qualified —
    the transports send it verbatim (they only append a charset to
    types that lack one)."""
    return "text/plain; version=0.0.4; charset=utf-8"


def parse_exposition(text: str) -> Dict[str, float]:
    """Parse Prometheus text back into ``{'name{labels}': value}`` —
    shared by bench.py's before/after scrape deltas and the conformance
    tests. Escapes inside label values are preserved verbatim (the key
    is the raw sample name as rendered)."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        # the value is the last whitespace-separated token; the sample
        # name may contain spaces only inside a quoted label value
        idx = line.rfind(" ")
        if idx <= 0:
            continue
        name, value = line[:idx].strip(), line[idx + 1:]
        try:
            out[name] = float(value)
        except ValueError:
            continue
    return out


# --- exposition analysis helpers (fleet consumers) ---
#
# Shared by `pio top`, the telemetry collector (utils/telemetry.py), and
# bench.py: everything a scrape CONSUMER needs to turn raw exposition
# text back into typed samples, per-family sums, and reconstructed
# quantiles. Kept here (not in tools/) because the collector tier is
# library code.

_LABEL_PAIR_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:\\.|[^"\\])*)"'
)

_UNESCAPE = {"\\\\": "\\", '\\"': '"', "\\n": "\n"}


def _unescape_label_value(v: str) -> str:
    out: List[str] = []
    i = 0
    while i < len(v):
        pair = v[i : i + 2]
        if pair in _UNESCAPE:
            out.append(_UNESCAPE[pair])
            i += 2
        else:
            out.append(v[i])
            i += 1
    return "".join(out)


def sample_family_name(sample_key: str) -> str:
    """``pio_foo_total{a="b"}`` → ``pio_foo_total``."""
    return sample_key.split("{", 1)[0]


def sample_label_value(sample_key: str, label: str) -> Optional[str]:
    """One label's (still-escaped) value from a rendered sample key."""
    m = re.search(rf'{label}="((?:\\.|[^"\\])*)"', sample_key)
    return m.group(1) if m else None


def parse_labels(sample_key: str) -> Tuple[Tuple[str, str], ...]:
    """The label set of a rendered sample key as ordered (name, value)
    pairs, with exposition escapes undone — the representation the
    federation layer merges and re-renders on."""
    if "{" not in sample_key:
        return ()
    body = sample_key.split("{", 1)[1].rsplit("}", 1)[0]
    return tuple(
        (name, _unescape_label_value(value))
        for name, value in _LABEL_PAIR_RE.findall(body)
    )


def parse_exposition_families(text: str) -> "Dict[str, dict]":
    """Parse Prometheus text into typed families::

        {family: {"kind": "counter"|"gauge"|"histogram"|"untyped",
                  "help": str,
                  "samples": [(sample_name, labels, value), ...]}}

    ``sample_name`` keeps histogram suffixes (``_bucket``/``_sum``/
    ``_count``) and ``labels`` is the ordered, unescaped pair tuple from
    :func:`parse_labels`. This is the typed complement of
    :func:`parse_exposition` — the federation layer needs the TYPE line
    to know whether samples sum (counters, histogram buckets) or keep
    per-instance identity (gauges)."""
    families: Dict[str, dict] = {}

    def family_for(sample_name: str) -> dict:
        # histogram samples carry suffixes; map them onto their family
        base = sample_name
        for suffix in ("_bucket", "_sum", "_count"):
            if sample_name.endswith(suffix):
                candidate = sample_name[: -len(suffix)]
                if candidate in families:
                    base = candidate
                    break
        fam = families.get(base)
        if fam is None:
            fam = families[base] = {
                "kind": "untyped", "help": "", "samples": [],
            }
        return fam

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            if len(parts) >= 3:
                fam = families.setdefault(
                    parts[2], {"kind": "untyped", "help": "", "samples": []}
                )
                fam["help"] = parts[3] if len(parts) > 3 else ""
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ", 3)
            if len(parts) >= 4:
                fam = families.setdefault(
                    parts[2], {"kind": "untyped", "help": "", "samples": []}
                )
                fam["kind"] = parts[3]
            continue
        if line.startswith("#"):
            continue
        idx = line.rfind(" ")
        if idx <= 0:
            continue
        key, raw_value = line[:idx].strip(), line[idx + 1 :]
        try:
            value = float(raw_value)
        except ValueError:
            continue
        sample_name = sample_family_name(key)
        family_for(sample_name)["samples"].append(
            (sample_name, parse_labels(key), value)
        )
    return families


def counter_sum(samples: Dict[str, float], family: str) -> float:
    """Sum a counter family across its label sets (flat
    :func:`parse_exposition` samples)."""
    total = 0.0
    for key, value in samples.items():
        if sample_family_name(key) == family:
            total += value
    return total


def gauge_max(samples: Dict[str, float], family: str) -> Optional[float]:
    vals = [
        v for k, v in samples.items() if sample_family_name(k) == family
    ]
    return max(vals) if vals else None


_LE_RE = re.compile(r'le="([^"]+)"')


def histogram_quantile_from_samples(
    samples: Dict[str, float], family: str, q: float
) -> Optional[float]:
    """Quantile from the exposition's cumulative ``_bucket`` samples,
    summed across label sets (bounds are fixed per family, so cumulative
    vectors add — the SO_REUSEPORT merge property)."""
    by_le: Dict[float, float] = {}
    for key, value in samples.items():
        if sample_family_name(key) != f"{family}_bucket":
            continue
        m = _LE_RE.search(key)
        if not m:
            continue
        le = m.group(1)
        bound = float("inf") if le == "+Inf" else float(le)
        by_le[bound] = by_le.get(bound, 0.0) + value
    if not by_le:
        return None
    bounds = sorted(b for b in by_le if b != float("inf"))
    cum = [by_le[b] for b in bounds] + [by_le.get(float("inf"), 0.0)]
    counts = [int(c - (cum[i - 1] if i else 0.0)) for i, c in enumerate(cum)]
    if sum(counts) <= 0:
        return None
    return quantile_from_buckets(bounds, counts, q)


# THE process-global registry (one per worker process; an SO_REUSEPORT
# fleet aggregates by scraping every worker and merging, see
# merge_snapshots). utils/metrics.py is the one sanctioned home for
# module-level metric state — tests/test_lint.py polices the rest of
# the package.
REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return REGISTRY
