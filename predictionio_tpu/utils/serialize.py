"""Model (de)serialization: pytrees <-> bytes.

The reference Kryo-serializes trained models into the MODELDATA store
(core/.../workflow/CoreWorkflow.scala:71-76, KryoInstantiator
CreateServer.scala:64-78). Here models are arbitrary Python objects whose
pytree leaves may be device-resident jax.Arrays; serialization first pulls
leaves to host numpy (one device->host transfer per leaf) so the blob is
device-independent, then pickles.
"""

from __future__ import annotations

import pickle
from typing import Any

import jax
import numpy as np


def to_host(pytree: Any) -> Any:
    """Replace device arrays with host numpy arrays throughout a pytree."""

    def pull(leaf):
        if isinstance(leaf, jax.Array):
            return np.asarray(leaf)
        return leaf

    return jax.tree_util.tree_map(pull, pytree)


def dumps_model(models: Any) -> bytes:
    return pickle.dumps(to_host(models), protocol=pickle.HIGHEST_PROTOCOL)


def loads_model(data: bytes) -> Any:
    return pickle.loads(data)
