"""Trace-correlated structured logging.

Every module in this package already logs through its own
``logging.getLogger(__name__)``; this module supplies the HANDLER layer:
a JSON-lines formatter (``PIO_LOG_FORMAT=json``) whose records carry the
ambient trace/span ids from ``utils.tracing``'s contextvar, and a text
formatter (the default) that appends ``traceId=…`` when a trace is
ambient. Either way, a log line emitted anywhere under a traced request
— the event server's insert path, a gateway RPC, a continuous-training
round (every PhaseTimer mints a trace) — joins against the span dump at
``/debug/traces.json`` on the ``traceId`` field, so "what did this
request log" is one grep, not a timestamp correlation exercise.

JSON field contract (docs/OBSERVABILITY.md):

    ts       ISO-8601 UTC with milliseconds
    level    logging level name
    logger   dotted module logger name
    message  rendered message
    traceId  ambient (or record-supplied) trace id — the join key
    spanId   ambient (or record-supplied) span id
    exc      traceback text, when the record carries exc_info
    + any extra= fields the call site attached (json-encodable values)

Call sites never change: ``logger.info(...)`` keeps working, and a
transport that wants an explicit id on a record passes
``extra={"traceId": tid}`` (which wins over the ambient context —
transport-layer errors fire outside any ``tracing.use`` block).
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
import os
import sys
from typing import Optional

__all__ = ["JsonFormatter", "TextFormatter", "setup_logging"]

# logging.LogRecord attributes that are plumbing, not payload — anything
# ELSE on a record (extra= fields) is emitted as a JSON field
_RESERVED = frozenset(
    logging.LogRecord(
        "", 0, "", 0, "", (), None
    ).__dict__
) | {"message", "asctime", "taskName"}


def _ambient_ids() -> "tuple[Optional[str], Optional[str]]":
    from predictionio_tpu.utils import tracing as _tracing

    ctx = _tracing.current()
    if ctx is None:
        return None, None
    return ctx.trace_id, ctx.span_id


class JsonFormatter(logging.Formatter):
    """One JSON object per line; trace ids from the record's ``extra``
    fields when present, the ambient tracing contextvar otherwise."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": _dt.datetime.fromtimestamp(
                record.created, _dt.timezone.utc
            ).isoformat(timespec="milliseconds"),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        trace_id = getattr(record, "traceId", None)
        span_id = getattr(record, "spanId", None)
        if trace_id is None:
            trace_id, ambient_span = _ambient_ids()
            if span_id is None:
                span_id = ambient_span
        if trace_id:
            out["traceId"] = trace_id
        if span_id:
            out["spanId"] = span_id
        for key, value in record.__dict__.items():
            if key in _RESERVED or key in ("traceId", "spanId"):
                continue
            try:
                json.dumps(value)
            except (TypeError, ValueError):
                value = repr(value)
            out[key] = value
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out)


class TextFormatter(logging.Formatter):
    """The human format the CLI always printed, plus the trace join key
    when one is ambient (or attached): ``[INFO] [pkg.mod] message
    traceId=abc123``."""

    def format(self, record: logging.LogRecord) -> str:
        base = f"[{record.levelname}] [{record.name}] {record.getMessage()}"
        trace_id = getattr(record, "traceId", None)
        if trace_id is None:
            trace_id, _ = _ambient_ids()
        if trace_id:
            base += f" traceId={trace_id}"
        if record.exc_info:
            base += "\n" + self.formatException(record.exc_info)
        return base


def make_formatter(fmt: Optional[str] = None) -> logging.Formatter:
    fmt = (fmt or os.environ.get("PIO_LOG_FORMAT") or "text").lower()
    if fmt == "json":
        return JsonFormatter()
    if fmt == "text":
        return TextFormatter()
    raise ValueError(f"PIO_LOG_FORMAT must be json|text, got {fmt!r}")


def setup_logging(
    level: int = logging.INFO,
    fmt: Optional[str] = None,
    stream=None,
) -> logging.Handler:
    """Install the structured handler on the root logger (CLI entry
    points call this; library importers never do — a library must not
    hijack its host's logging). Idempotent: a handler this function
    installed earlier is replaced, foreign handlers are left alone."""
    root = logging.getLogger()
    handler = logging.StreamHandler(stream if stream is not None else sys.stderr)
    handler.setFormatter(make_formatter(fmt))
    handler._pio_structured = True  # type: ignore[attr-defined]
    for h in list(root.handlers):
        if getattr(h, "_pio_structured", False):
            root.removeHandler(h)
    root.addHandler(handler)
    root.setLevel(level)
    return handler
