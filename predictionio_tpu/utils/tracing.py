"""Lightweight end-to-end request tracing.

A trace id accepted via the ``X-PIO-Trace-Id`` header at an ingest or
serving entry point (the header is the opt-in: untraced hot-path
requests record nothing, so traced requests can't be evicted by bulk
traffic and the hot path never touches the span ring's lock) is
propagated — explicitly through the engine server's batching executor,
via a ``contextvars`` context through the group-commit committer and
the storage-gateway RPC client — so one request's path is a chain of
spans. Training runs mint their own trace per ``PhaseTimer``:

    serving:  http → batch → predict
    ingest:   http → insert → group-commit-flush
    remote:   http → rpc:<dao>.<method> (gateway process) → flush

Spans land in a bounded process-global ring buffer (deque, oldest
evicted first) dumpable via ``GET /debug/traces.json`` on every server
(access-key gated) and ``pio trace``. This is deliberately NOT a
distributed-tracing stack: no sampling config, no exporters, no clock
sync — just enough to answer "where did this request's time go" across
the subsystems this repo actually has. For device-side timelines, wrap
the training call in ``utils.profiling.trace`` (jax.profiler).

Like utils/metrics.py, this module is a sanctioned home for
module-level observability state (tests/test_lint.py polices the rest
of the package).
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import secrets
import threading
import time
from typing import Dict, Iterator, List, NamedTuple, Optional

__all__ = [
    "TRACE_HEADER",
    "PARENT_HEADER",
    "TraceContext",
    "mint_trace_id",
    "new_span_id",
    "from_headers",
    "current",
    "use",
    "record_span",
    "span",
    "dump",
    "dump_since",
    "high_water",
    "clear",
    "format_trace",
]

TRACE_HEADER = "X-PIO-Trace-Id"
PARENT_HEADER = "X-PIO-Parent-Span"

# completed spans kept for /debug/traces.json; oldest evicted first
MAX_SPANS = 4096

_ID_RE_MAX = 64  # accepted header ids are clamped to this many chars


class TraceContext(NamedTuple):
    """What propagates: the trace id plus the span id of the caller
    (the parent of whatever span the callee records)."""

    trace_id: str
    span_id: str


def mint_trace_id() -> str:
    return secrets.token_hex(8)


def new_span_id() -> str:
    return secrets.token_hex(4)


def _sanitize(raw: str) -> str:
    """Header-supplied ids go into JSON dumps and log lines verbatim —
    keep them printable and bounded."""
    cleaned = "".join(c for c in raw if c.isalnum() or c in "-_")
    return cleaned[:_ID_RE_MAX]


def from_headers(
    headers: Optional[Dict[str, str]],
) -> "tuple[TraceContext, Optional[str]]":
    """Trace context for one inbound request: the ``X-PIO-Trace-Id``
    header when present (client-chosen correlation id), a fresh mint
    otherwise. Returns ``(ctx, inbound_parent_span_id)``: ``ctx.span_id``
    is the id the entry-point span records under (children chain on it);
    the inbound parent — the remote caller's span on a cross-process hop
    — becomes the entry span's ``parentId``."""
    trace_id = ""
    parent = ""
    if headers:
        trace_id = _sanitize(headers.get(TRACE_HEADER.lower(), "") or "")
        parent = _sanitize(headers.get(PARENT_HEADER.lower(), "") or "")
    if not trace_id:
        trace_id = mint_trace_id()
    return TraceContext(trace_id, new_span_id()), (parent or None)


# the contextvar carries the trace across same-thread call stacks
# (event-server insert -> sqlite committer submit, storage client RPCs);
# cross-THREAD propagation (the batching executor, the committer's flush
# thread) is explicit — items carry their TraceContext.
_CURRENT: "contextvars.ContextVar[Optional[TraceContext]]" = (
    contextvars.ContextVar("pio_trace", default=None)
)


def current() -> Optional[TraceContext]:
    return _CURRENT.get()


@contextlib.contextmanager
def use(ctx: Optional[TraceContext]) -> Iterator[None]:
    """Bind ``ctx`` as the ambient trace for the block (no-op on None)."""
    token = _CURRENT.set(ctx)
    try:
        yield
    finally:
        _CURRENT.reset(token)


_SPANS: "collections.deque" = collections.deque(maxlen=MAX_SPANS)
_SPANS_LOCK = threading.Lock()
# monotonic per-process span sequence: every recorded span gets the next
# value as its ``seq`` field, so a remote consumer (the telemetry
# collector, utils/telemetry.py) can pull the ring INCREMENTALLY with
# ``?since=<seq>`` instead of re-downloading all 4096 spans per poll.
# The counter never resets within a process; a fresh process starts at 0
# (the collector treats a high-water mark BELOW its cursor as a restart
# and re-pulls from scratch).
_SEQ = [0]


def record_span(
    name: str,
    trace_id: str,
    span_id: Optional[str] = None,
    parent_id: Optional[str] = None,
    start_s: Optional[float] = None,
    duration_s: float = 0.0,
    attrs: Optional[dict] = None,
) -> str:
    """Append one completed span to the ring buffer. ``start_s`` is
    epoch seconds (wall clock; defaults to now - duration)."""
    sid = span_id or new_span_id()
    now = time.time()
    entry = {
        "traceId": trace_id,
        "spanId": sid,
        "parentId": parent_id,
        "name": name,
        "startMs": round(
            ((now - duration_s) if start_s is None else start_s) * 1000.0, 3
        ),
        "durationMs": round(duration_s * 1000.0, 3),
    }
    if attrs:
        entry["attrs"] = attrs
    with _SPANS_LOCK:
        _SEQ[0] += 1
        entry["seq"] = _SEQ[0]
        _SPANS.append(entry)
    return sid


@contextlib.contextmanager
def span(
    name: str,
    ctx: Optional[TraceContext] = None,
    attrs: Optional[dict] = None,
) -> Iterator[Optional[TraceContext]]:
    """Record a span around a block, parented on ``ctx`` (or the ambient
    context). The child context becomes the AMBIENT context for the
    block, so nested subsystems (committer submit, gateway RPC client)
    chain under it without explicit plumbing. No-op (yields None) when
    there is no trace."""
    parent = ctx if ctx is not None else current()
    if parent is None:
        yield None
        return
    child = TraceContext(parent.trace_id, new_span_id())
    token = _CURRENT.set(child)
    t0 = time.time()
    try:
        yield child
    finally:
        _CURRENT.reset(token)
        record_span(
            name,
            parent.trace_id,
            span_id=child.span_id,
            parent_id=parent.span_id,
            start_s=t0,
            duration_s=time.time() - t0,
            attrs=attrs,
        )


def dump(
    trace_id: Optional[str] = None, limit: int = MAX_SPANS
) -> List[dict]:
    """Spans (oldest first), optionally filtered to one trace. The
    filter is sanitized the same way inbound header ids are, so a
    client-chosen id with stripped characters still matches the id its
    spans were recorded under."""
    with _SPANS_LOCK:
        spans = list(_SPANS)
    if trace_id:
        trace_id = _sanitize(trace_id)
        spans = [s for s in spans if s["traceId"] == trace_id]
    return spans[-limit:]


def high_water() -> int:
    """The newest recorded span's sequence number (0 before any span)."""
    with _SPANS_LOCK:
        return _SEQ[0]


def dump_since(
    since: int,
    limit: int = MAX_SPANS,
    trace_id: Optional[str] = None,
) -> "tuple[List[dict], int]":
    """Incremental dump: ``(spans with seq > since, high-water mark)``.

    The cursor contract behind ``/debug/traces.json?since=<seq>``: a
    consumer feeds back the returned high-water mark on its next pull
    and only ever downloads new spans. ``since=0`` is the full ring
    (same content as :func:`dump`), and the high-water mark advances
    even when the matching spans were already evicted — the consumer's
    cursor never sticks behind a burst."""
    with _SPANS_LOCK:
        hwm = _SEQ[0]
        spans = [s for s in _SPANS if s["seq"] > since]
    if trace_id:
        trace_id = _sanitize(trace_id)
        spans = [s for s in spans if s["traceId"] == trace_id]
    return spans[-limit:], hwm


def clear() -> None:
    with _SPANS_LOCK:
        _SPANS.clear()
        _SEQ[0] = 0


def format_trace(spans: List[dict]) -> str:
    """Indent spans under their parents (the ``pio trace`` renderer).
    Orphans (parent evicted from the ring) print at the root."""
    by_parent: Dict[Optional[str], List[dict]] = {}
    ids = {s["spanId"] for s in spans}
    for s in sorted(spans, key=lambda x: x["startMs"]):
        parent = s.get("parentId")
        by_parent.setdefault(parent if parent in ids else None, []).append(s)

    lines: List[str] = []

    def walk(parent: Optional[str], depth: int) -> None:
        for s in by_parent.get(parent, []):
            attrs = s.get("attrs")
            lines.append(
                f"{'  ' * depth}{s['name']}: {s['durationMs']:.3f}ms"
                + (f"  {attrs}" if attrs else "")
            )
            walk(s["spanId"], depth + 1)

    walk(None, 0)
    return "\n".join(lines)
