"""Per-phase timers + jax.profiler integration.

The reference's only observability is coarse serving-time bookkeeping
(CreateServer.scala:399-404) plus the Spark web UI (SURVEY.md §5 —
"plan for jax.profiler traces + per-phase timers as first-class"). This
module provides both:

- ``PhaseTimer``: named wall-clock phases with nesting, collected per
  workflow run and queryable/printable for run summaries;
- ``trace(dir)``: context manager around ``jax.profiler.trace`` emitting
  a TensorBoard-loadable device trace when a profile dir is set.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import time
from typing import Dict, Iterator, List, Optional

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class PhaseRecord:
    name: str
    seconds: float
    depth: int
    start: float  # perf_counter at phase entry — orders the summary
    # True for phases that ran CONCURRENTLY under another recorded phase
    # (the streaming pipeline's scan/fold/compile run under the train
    # phase's wall clock): excluded from wall-clock totals so the
    # summary's arithmetic stays honest.
    overlapped: bool = False


class PhaseTimer:
    """Collects named wall-clock phases (nested phases indent).

    Every timer owns a trace id (``utils.tracing``) and emits each phase
    as a span into the process trace buffer, nested by the phase stack —
    so a continuous-training round is ONE coherent trace from store poll
    to checkpoint, dumpable via any server's /debug/traces.json or
    ``pio trace`` beside the text summary."""

    def __init__(self):
        from predictionio_tpu.utils import tracing as _tracing

        self.records: List[PhaseRecord] = []
        self.notes: Dict[str, object] = {}
        self._depth = 0
        self.trace_id = _tracing.mint_trace_id()
        self._span_stack: List[str] = []

    def note(self, key: str, value) -> None:
        """Attach a non-duration annotation (cache outcomes, delta
        sizes) shown in the summary — last write per key wins."""
        self.notes[key] = value

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        from predictionio_tpu.utils import tracing as _tracing

        start = time.perf_counter()
        start_wall = time.time()
        # the span id is minted at ENTRY so nested phases can parent on
        # it even though spans are recorded (as completed) at exit
        span_id = _tracing.new_span_id()
        parent_id = self._span_stack[-1] if self._span_stack else None
        self._span_stack.append(span_id)
        self._depth += 1
        try:
            yield
        finally:
            self._depth -= 1
            self._span_stack.pop()
            elapsed = time.perf_counter() - start
            self.records.append(
                PhaseRecord(name, elapsed, self._depth, start)
            )
            _tracing.record_span(
                f"phase:{name}", self.trace_id, span_id=span_id,
                parent_id=parent_id, start_s=start_wall,
                duration_s=elapsed,
            )
            logger.info("phase %s: %.3fs", name, elapsed)

    def add(
        self, name: str, seconds: float, overlapped: bool = False
    ) -> None:
        """Record an externally-measured phase. ``overlapped=True``
        marks busy time that was hidden under another phase (pipelined
        work) rather than serial wall clock."""
        from predictionio_tpu.utils import tracing as _tracing

        self.records.append(
            PhaseRecord(
                name, seconds, self._depth + 1, time.perf_counter(),
                overlapped=overlapped,
            )
        )
        _tracing.record_span(
            f"phase:{name}", self.trace_id,
            parent_id=self._span_stack[-1] if self._span_stack else None,
            duration_s=seconds,
            attrs={"overlapped": True} if overlapped else None,
        )

    def totals(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for r in self.records:
            out[r.name] = out.get(r.name, 0.0) + r.seconds
        return out

    def overlapped_total(self) -> float:
        """Busy seconds that were hidden under other phases — the work
        the pipeline took OFF the wall clock."""
        return sum(r.seconds for r in self.records if r.overlapped)

    def summary(self) -> str:
        # chronological, parents before their children (same start order,
        # shallower first)
        ordered = sorted(self.records, key=lambda r: (r.start, r.depth))
        lines = [
            f"{'  ' * r.depth}{r.name}: {r.seconds:.3f}s"
            + (" [overlapped]" if r.overlapped else "")
            for r in ordered
        ]
        hidden = self.overlapped_total()
        if hidden:
            lines.append(
                f"(pipelining hid {hidden:.3f}s of host/compile work "
                "under the phases above)"
            )
        if self.notes:
            lines.append(
                "notes: "
                + " ".join(f"{k}={v}" for k, v in self.notes.items())
            )
        return "\n".join(lines)


@contextlib.contextmanager
def trace(profile_dir: Optional[str]) -> Iterator[None]:
    """jax.profiler.trace around a block when profile_dir is set; no-op
    otherwise. View with TensorBoard's profile plugin or Perfetto."""
    if not profile_dir:
        yield
        return
    import jax

    logger.info("writing jax profiler trace to %s", profile_dir)
    with jax.profiler.trace(profile_dir):
        yield
