"""Per-phase timers + jax.profiler integration.

The reference's only observability is coarse serving-time bookkeeping
(CreateServer.scala:399-404) plus the Spark web UI (SURVEY.md §5 —
"plan for jax.profiler traces + per-phase timers as first-class"). This
module provides both:

- ``PhaseTimer``: named wall-clock phases with nesting, collected per
  workflow run and queryable/printable for run summaries;
- ``trace(dir)``: context manager around ``jax.profiler.trace`` emitting
  a TensorBoard-loadable device trace when a profile dir is set;
- :class:`ProfileCapture` + :func:`profile_route`: the on-demand,
  secret-gated ``POST /debug/profile?seconds=N`` capture every server
  exposes (``pio profile`` drives it) — same session machinery as
  ``trace``, so CLI- and HTTP-triggered captures are layout-identical.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import os
import threading
import time
from typing import Dict, Iterator, List, Optional

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class PhaseRecord:
    name: str
    seconds: float
    depth: int
    start: float  # perf_counter at phase entry — orders the summary
    # True for phases that ran CONCURRENTLY under another recorded phase
    # (the streaming pipeline's scan/fold/compile run under the train
    # phase's wall clock): excluded from wall-clock totals so the
    # summary's arithmetic stays honest.
    overlapped: bool = False


class PhaseTimer:
    """Collects named wall-clock phases (nested phases indent).

    Every timer owns a trace id (``utils.tracing``) and emits each phase
    as a span into the process trace buffer, nested by the phase stack —
    so a continuous-training round is ONE coherent trace from store poll
    to checkpoint, dumpable via any server's /debug/traces.json or
    ``pio trace`` beside the text summary."""

    def __init__(self):
        from predictionio_tpu.utils import tracing as _tracing

        self.records: List[PhaseRecord] = []
        self.notes: Dict[str, object] = {}
        self._depth = 0
        self.trace_id = _tracing.mint_trace_id()
        self._span_stack: List[str] = []

    def note(self, key: str, value) -> None:
        """Attach a non-duration annotation (cache outcomes, delta
        sizes) shown in the summary — last write per key wins."""
        self.notes[key] = value

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        from predictionio_tpu.utils import tracing as _tracing

        start = time.perf_counter()
        start_wall = time.time()
        # the span id is minted at ENTRY so nested phases can parent on
        # it even though spans are recorded (as completed) at exit
        span_id = _tracing.new_span_id()
        parent_id = self._span_stack[-1] if self._span_stack else None
        self._span_stack.append(span_id)
        self._depth += 1
        try:
            yield
        finally:
            self._depth -= 1
            self._span_stack.pop()
            elapsed = time.perf_counter() - start
            self.records.append(
                PhaseRecord(name, elapsed, self._depth, start)
            )
            _tracing.record_span(
                f"phase:{name}", self.trace_id, span_id=span_id,
                parent_id=parent_id, start_s=start_wall,
                duration_s=elapsed,
            )
            logger.info("phase %s: %.3fs", name, elapsed)

    def add(
        self, name: str, seconds: float, overlapped: bool = False
    ) -> None:
        """Record an externally-measured phase. ``overlapped=True``
        marks busy time that was hidden under another phase (pipelined
        work) rather than serial wall clock."""
        from predictionio_tpu.utils import tracing as _tracing

        self.records.append(
            PhaseRecord(
                name, seconds, self._depth + 1, time.perf_counter(),
                overlapped=overlapped,
            )
        )
        _tracing.record_span(
            f"phase:{name}", self.trace_id,
            parent_id=self._span_stack[-1] if self._span_stack else None,
            duration_s=seconds,
            attrs={"overlapped": True} if overlapped else None,
        )

    def totals(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for r in self.records:
            out[r.name] = out.get(r.name, 0.0) + r.seconds
        return out

    def overlapped_total(self) -> float:
        """Busy seconds that were hidden under other phases — the work
        the pipeline took OFF the wall clock."""
        return sum(r.seconds for r in self.records if r.overlapped)

    def summary(self) -> str:
        # chronological, parents before their children (same start order,
        # shallower first)
        ordered = sorted(self.records, key=lambda r: (r.start, r.depth))
        lines = [
            f"{'  ' * r.depth}{r.name}: {r.seconds:.3f}s"
            + (" [overlapped]" if r.overlapped else "")
            for r in ordered
        ]
        hidden = self.overlapped_total()
        if hidden:
            lines.append(
                f"(pipelining hid {hidden:.3f}s of host/compile work "
                "under the phases above)"
            )
        if self.notes:
            lines.append(
                "notes: "
                + " ".join(f"{k}={v}" for k, v in self.notes.items())
            )
        return "\n".join(lines)


# --- on-demand profiler capture (the device-observability round) ---
#
# One capture machinery for BOTH entry points: `pio train --profile-dir`
# (the trace() context manager below, driven by workflow_params'
# profile_dir) and the secret-gated `POST /debug/profile?seconds=N`
# endpoint every server exposes. Both funnel through _profiler_session,
# so a CLI-launched capture and an HTTP-triggered one produce IDENTICAL
# trace layouts (jax's plugins/profile/<run>/ tree) — before this
# round, the HTTP path simply did not exist and the jax.profiler hook
# only fired when a train run was launched with --profile-dir.

# serializes jax.profiler sessions process-wide: jax refuses nested /
# concurrent traces, so a training --profile-dir capture and an HTTP
# capture must take turns
_SESSION_LOCK = threading.Lock()


@contextlib.contextmanager
def _session_body(profile_dir: str) -> Iterator[None]:
    """The jax.profiler session itself — callers MUST hold
    :data:`_SESSION_LOCK` (``_profiler_session`` blocks for it; the
    HTTP capture acquires it non-blockingly so a busy profiler answers
    409 instead of parking a route-pool worker)."""
    import jax

    os.makedirs(profile_dir, exist_ok=True)
    logger.info("writing jax profiler trace to %s", profile_dir)
    with jax.profiler.trace(profile_dir):
        yield


@contextlib.contextmanager
def _profiler_session(profile_dir: str) -> Iterator[None]:
    """THE code path that touches jax.profiler: makedirs + trace,
    serialized on the process-wide session lock."""
    with _SESSION_LOCK:
        with _session_body(profile_dir):
            yield


@contextlib.contextmanager
def trace(profile_dir: Optional[str]) -> Iterator[None]:
    """jax.profiler.trace around a block when profile_dir is set; no-op
    otherwise. View with TensorBoard's profile plugin or Perfetto.
    (The context-manager API over the shared capture machinery — the
    HTTP ``/debug/profile`` endpoint rides the same session path.)"""
    if not profile_dir:
        yield
        return
    with _profiler_session(profile_dir):
        yield


def _m_captures() -> object:
    from predictionio_tpu.utils import metrics as _metrics

    return _metrics.get_registry().counter(
        "pio_profile_captures_total",
        "On-demand profiler captures by outcome (ok / busy = a capture "
        "or --profile-dir session was already running / error)",
        labels=("outcome",),
    )


class ProfileCapture:
    """Bounded on-demand capture driver behind ``POST /debug/profile``.

    One capture at a time (jax.profiler cannot nest); the capture runs
    INLINE in the calling route-pool thread for ``seconds`` (clamped to
    :attr:`MAX_SECONDS`), zips the produced trace tree, and returns the
    archive base64-encoded in the JSON response (the HTTP adapters
    render JSON/str payloads only — no binary framing needed). The
    spool directory is capped: only the newest :attr:`MAX_SPOOLED`
    capture trees are kept on disk."""

    MAX_SECONDS = 120.0
    MAX_SPOOLED = 4

    def __init__(self, spool_dir: Optional[str] = None):
        self._spool_dir = spool_dir
        self._lock = threading.Lock()
        self._busy = False
        self._last: Optional[dict] = None

    @property
    def spool_dir(self) -> str:
        if self._spool_dir is None:
            import tempfile

            self._spool_dir = os.path.join(
                tempfile.gettempdir(), "pio-profile-spool"
            )
        return self._spool_dir

    def status(self) -> dict:
        with self._lock:
            last = None
            if self._last is not None:
                last = {
                    k: v
                    for k, v in self._last.items()
                    if k != "archive_b64"
                }
            return {"running": self._busy, "last": last}

    def last(self) -> Optional[dict]:
        with self._lock:
            return self._last

    def capture(self, seconds: float) -> "tuple[int, dict]":
        """Run one bounded capture; returns ``(http_status, payload)``.
        409 while another capture (or a --profile-dir training session)
        holds the profiler; the payload carries the zipped trace tree
        base64-encoded plus its file listing."""
        seconds = max(0.1, min(float(seconds), self.MAX_SECONDS))
        with self._lock:
            if self._busy:
                _m_captures().labels(outcome="busy").inc()
                return 409, {"message": "a profile capture is already running"}
            self._busy = True
        try:
            # non-blocking probe AND hold: a --profile-dir training
            # session owning the lock answers 409 immediately, and the
            # lock stays held through the capture so a session starting
            # in between cannot park this route-pool worker
            if not _SESSION_LOCK.acquire(blocking=False):
                _m_captures().labels(outcome="busy").inc()
                return 409, {
                    "message": "a --profile-dir profiler session is active"
                }
            started = time.time()
            cap_dir = os.path.join(
                self.spool_dir, f"capture-{int(started * 1000)}"
            )
            try:
                with _session_body(cap_dir):
                    time.sleep(seconds)
                payload = self._archive(cap_dir, started, seconds)
            except Exception as e:
                logger.exception("profile capture failed")
                _m_captures().labels(outcome="error").inc()
                return 500, {"message": f"capture failed: {e}"}
            finally:
                _SESSION_LOCK.release()
            self._trim_spool()
            with self._lock:
                self._last = payload
            _m_captures().labels(outcome="ok").inc()
            return 200, payload
        finally:
            with self._lock:
                self._busy = False

    def _archive(self, cap_dir: str, started: float, seconds: float) -> dict:
        import base64
        import io
        import zipfile

        names: list = []
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
            for root, _dirs, files in os.walk(cap_dir):
                for name in sorted(files):
                    full = os.path.join(root, name)
                    rel = os.path.relpath(full, cap_dir)
                    zf.write(full, rel)
                    names.append(rel)
        data = buf.getvalue()
        return {
            "startedAt": started,
            "seconds": seconds,
            "dir": cap_dir,
            "files": names,
            "archiveBytes": len(data),
            "archive_b64": base64.b64encode(data).decode("ascii"),
        }

    def _trim_spool(self) -> None:
        try:
            caps = sorted(
                d
                for d in os.listdir(self.spool_dir)
                if d.startswith("capture-")
            )
        except OSError:
            return
        import shutil

        for stale in caps[: -self.MAX_SPOOLED]:
            shutil.rmtree(
                os.path.join(self.spool_dir, stale), ignore_errors=True
            )


# THE process-global capture driver (all three servers' /debug/profile
# routes share it — one profiler, one spool).
_CAPTURE = ProfileCapture()


def get_capture() -> ProfileCapture:
    return _CAPTURE


def profile_route(
    method: str, query, authorized: bool
) -> "tuple[int, dict]":
    """The shared ``/debug/profile`` request core (all three servers
    route here after their own auth gate, like http.traces_payload):
    ``POST ?seconds=N`` runs one bounded capture and returns the
    archive; ``GET`` returns capture status (and the last archive with
    ``?archive=1``)."""
    if not authorized:
        return 401, {"message": "invalid or missing credentials"}
    cap = get_capture()
    if method == "POST":
        raw = (query or {}).get("seconds", "2")
        try:
            seconds = float(raw)
        except (TypeError, ValueError):
            return 400, {"message": f"invalid seconds {raw!r}"}
        return cap.capture(seconds)
    if method == "GET":
        if (query or {}).get("archive"):
            last = cap.last()
            if last is None:
                return 404, {"message": "no capture taken yet"}
            return 200, last
        return 200, cap.status()
    return 405, {"message": "Method not allowed."}
