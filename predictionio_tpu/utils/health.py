"""Runtime health: the heartbeat/watchdog registry behind /healthz+/readyz.

Every server in this package is a frontend over a set of background
daemons — sqlite group-commit committer threads, the segment compactor,
the continuous-training loop, the engine server's batching executor and
feedback drainer. A fleet operator (or the zero-downtime hot-swap loop
the ROADMAP plans) needs two different answers from each process:

- **liveness** (``GET /healthz``): is the process serving at all? Always
  200 while the frontend can run the handler — restart-worthy only when
  it stops answering.
- **readiness** (``GET /readyz``): should traffic be routed here NOW?
  503 when the model/store is unavailable or a background daemon is
  *stalled* — registered, mid-work, and silent past its deadline (a
  wedged COMMIT, a hung compaction round). Idle daemons are healthy by
  definition: a committer parked on an empty queue has nothing to prove.

The registry is process-global (one process = one fleet worker, exactly
like utils/metrics.py, and this module is a sanctioned home for that
module-level observability state — tests/test_lint.py polices the rest
of the package). Daemons register a :class:`Heartbeat` and wrap each
unit of work in ``with hb.busy():`` (or call ``hb.beat()`` inside long
rounds); ``readiness()`` folds every registered heartbeat plus
server-supplied probes into one verdict. Beats are lock-cheap (a float
store + a counter inc), far off any hot path's noise floor.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

from predictionio_tpu.utils import metrics as _metrics

__all__ = [
    "Heartbeat",
    "heartbeat",
    "unregister",
    "heartbeats",
    "liveness",
    "readiness",
    "TTLProbe",
    "record_memory_gauges",
]

_PROCESS_START_MONOTONIC = time.monotonic()


def _beats_counter() -> "_metrics.Counter":
    return _metrics.get_registry().counter(
        "pio_heartbeat_beats_total",
        "Heartbeats recorded by background daemons",
        labels=("daemon",),
    )


def _stalled_gauge() -> "_metrics.Gauge":
    return _metrics.get_registry().gauge(
        "pio_daemons_stalled",
        "Registered background daemons currently stalled past deadline",
    )


class Heartbeat:
    """One daemon's watchdog state.

    ``busy()`` brackets a unit of work; ``stalled()`` is True only when
    the daemon is INSIDE a unit and has not beaten for ``deadline_s`` —
    so an idle daemon never degrades readiness, and recovery (the unit
    finally completing, or beating mid-round) clears the stall without
    any explicit reset. ``deadline_s`` is mutable so tests (and
    operators via server config) can tighten it.
    """

    def __init__(self, name: str, deadline_s: float):
        self.name = name
        self.deadline_s = float(deadline_s)
        self._lock = threading.Lock()
        self._last = time.monotonic()
        self._busy = 0
        self._counter = _beats_counter().labels(daemon=name)

    def beat(self) -> None:
        with self._lock:
            self._last = time.monotonic()
        self._counter.inc()

    @contextlib.contextmanager
    def busy(self) -> Iterator[None]:
        """Mark one unit of work in flight; beats on entry and exit so
        back-to-back units never look stalled."""
        with self._lock:
            self._busy += 1
            self._last = time.monotonic()
        self._counter.inc()
        try:
            yield
        finally:
            with self._lock:
                self._busy -= 1
                self._last = time.monotonic()

    def stalled(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        with self._lock:
            return self._busy > 0 and (now - self._last) > self.deadline_s

    def status(self, now: Optional[float] = None) -> dict:
        now = time.monotonic() if now is None else now
        with self._lock:
            busy, last = self._busy, self._last
        age = now - last
        return {
            "busy": busy,
            "sinceLastBeatSec": round(age, 3),
            "deadlineSec": self.deadline_s,
            "stalled": busy > 0 and age > self.deadline_s,
        }


_HEARTBEATS: Dict[str, Heartbeat] = {}
_HEARTBEATS_LOCK = threading.Lock()


def heartbeat(name: str, deadline_s: float = 60.0) -> Heartbeat:
    """Get-or-create the heartbeat named ``name``. Daemons that share a
    name (two executors of one process) share the heartbeat — either
    one stalling degrades readiness, which is the verdict an operator
    wants for the whole process. The first registration pins the
    deadline; adjust ``hb.deadline_s`` directly to change it."""
    hb = _HEARTBEATS.get(name)
    if hb is None:
        with _HEARTBEATS_LOCK:
            hb = _HEARTBEATS.get(name)
            if hb is None:
                hb = Heartbeat(name, deadline_s)
                _HEARTBEATS[name] = hb
    return hb


def unregister(name: str) -> None:
    """Drop a heartbeat (clean daemon shutdown). Optional for busy-mode
    daemons — an idle leftover is healthy — but polite in processes that
    cycle many servers (tests)."""
    with _HEARTBEATS_LOCK:
        _HEARTBEATS.pop(name, None)


def heartbeats() -> List[Heartbeat]:
    with _HEARTBEATS_LOCK:
        return [_HEARTBEATS[k] for k in sorted(_HEARTBEATS)]


def liveness() -> dict:
    """The /healthz payload: cheap, allocation-light, never consults
    storage or daemons — liveness must answer even when readiness is
    degraded, or the orchestrator restarts a process that only needed
    traffic drained."""
    return {
        "status": "ok",
        "uptimeSec": round(time.monotonic() - _PROCESS_START_MONOTONIC, 3),
    }


class TTLProbe:
    """A readiness probe with a small result cache, so an unauthenticated
    /readyz poller cannot turn the probe's storage read into a
    request-rate storage load (the same guard CachedCompactionStatus
    applies to the compaction stats)."""

    def __init__(self, name: str, fn: Callable[[], None], ttl_s: float = 1.0):
        self.name = name
        self._fn = fn
        self._ttl_s = float(ttl_s)
        self._lock = threading.Lock()
        self._cached: Optional[Tuple[float, bool, str]] = None

    def check(self) -> Tuple[bool, str]:
        now = time.monotonic()
        with self._lock:
            cached = self._cached
            if cached is not None and now - cached[0] < self._ttl_s:
                return cached[1], cached[2]
        try:
            self._fn()
            ok, detail = True, "ok"
        except Exception as e:
            ok, detail = False, f"{type(e).__name__}: {e}"
        with self._lock:
            self._cached = (now, ok, detail)
        return ok, detail


def readiness(
    probes: Sequence[TTLProbe] = (),
) -> Tuple[bool, dict]:
    """The /readyz verdict: every server-supplied probe passes AND no
    registered daemon is stalled past its deadline. Returns ``(ok,
    payload)``; the payload names each failing component so the 503 is
    actionable without log spelunking."""
    now = time.monotonic()
    components: Dict[str, dict] = {}
    ok = True
    stalled = 0
    for hb in heartbeats():
        s = hb.status(now)
        if s["stalled"]:
            ok = False
            stalled += 1
            components[hb.name] = s
    _stalled_gauge().set(stalled)
    probe_out: Dict[str, str] = {}
    for p in probes:
        p_ok, detail = p.check()
        probe_out[p.name] = detail
        if not p_ok:
            ok = False
    payload = {
        "status": "ok" if ok else "unavailable",
        "daemons": len(heartbeats()),
        "stalledDaemons": components,
        "probes": probe_out,
    }
    return ok, payload


# --- process/device memory gauges (training-round resource telemetry) ---


def record_memory_gauges() -> dict:
    """Set ``pio_device_memory_bytes{device,stat}`` from each addressable
    device's ``memory_stats()`` (backends without the API — the CPU
    client — report nothing) and ``pio_host_rss_bytes`` from
    /proc/self/status (RSS fallback; absent off-Linux). Called once per
    training round — cheap, but not a hot-path instrument. Returns what
    it recorded (the round report includes it)."""
    reg = _metrics.get_registry()
    out: dict = {}
    try:
        import jax

        g = reg.gauge(
            "pio_device_memory_bytes",
            "Device memory from device.memory_stats(), where the backend "
            "provides it",
            labels=("device", "stat"),
        )
        for d in jax.local_devices():
            try:
                ms = d.memory_stats()
            except Exception:
                ms = None
            if not ms:
                continue
            for stat in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
                if stat in ms:
                    g.labels(device=str(d.id), stat=stat).set(float(ms[stat]))
                    out[f"device{d.id}.{stat}"] = int(ms[stat])
    except Exception:
        pass  # memory telemetry must never fail a training round
    rss = _read_rss_bytes()
    if rss is not None:
        reg.gauge(
            "pio_host_rss_bytes", "Resident set size of this process"
        ).set(float(rss))
        out["host_rss_bytes"] = rss
    return out


def _read_rss_bytes() -> Optional[int]:
    try:
        with open("/proc/self/statm") as f:
            fields = f.read().split()
        return int(fields[1]) * os.sysconf("SC_PAGE_SIZE")
    except Exception:
        return None
