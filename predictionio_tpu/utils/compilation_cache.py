"""Persistent XLA compilation cache for the framework's device programs.

No reference analog — the reference's JVM/Spark substrate has no
compilation step, while every first train/eval/serve here pays an XLA
compile (20-40 s for the fused ALS loop on a real TPU). Persisting
compiled executables across processes removes that cost from every run
after the first: `pio train` today, redeploys, repeated tuning sweeps,
and engine-server restarts all reuse yesterday's executables as long as
shapes (bucketed — ops/als.py pack_segments) and the jax/XLA version
match. JAX keys cache entries by program + compile options, so reuse is
always sound.

Layout: ``$PIO_COMPILATION_CACHE_DIR``, default
``$PIO_FS_BASEDIR/compilation_cache`` (beside the localfs/sqlite
storage universe). Set ``PIO_COMPILATION_CACHE_DIR=off`` to disable.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger(__name__)

_configured = False


def ensure_compilation_cache() -> Optional[str]:
    """Point JAX at the persistent cache directory (idempotent; best
    effort — failures log and fall back to in-memory-only caching).
    Returns the directory in use, or None when disabled/failed."""
    global _configured
    if _configured:
        import jax

        return jax.config.jax_compilation_cache_dir or None
    _configured = True
    path = os.environ.get("PIO_COMPILATION_CACHE_DIR")
    if path is not None and path.lower() in ("off", "none", "0", ""):
        return None
    if path is None:
        from predictionio_tpu.utils.fs import fs_basedir

        path = os.path.join(fs_basedir(), "compilation_cache")
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        # thresholds first: if any knob is missing on this jax version we
        # bail out BEFORE activating the on-disk cache, so a None return
        # is never half-configured.
        # Cache every program the framework compiles — the default 1 s
        # floor would skip the small serving/predict executables whose
        # cold compiles are exactly the deploy-time tail latency the
        # warm-up hook exists to hide.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        # bound on-disk growth (LRU eviction): tuning sweeps and jax/XLA
        # version bumps would otherwise accumulate entries forever
        jax.config.update("jax_compilation_cache_max_size", 4 * 1024**3)
        jax.config.update("jax_compilation_cache_dir", path)
        logger.info("XLA compilation cache at %s", path)
        return path
    except Exception as e:  # unwritable dir, old jax — never fatal
        try:
            import jax

            jax.config.update("jax_compilation_cache_dir", "")
        except Exception:
            pass
        logger.warning("compilation cache disabled: %s", e)
        return None
