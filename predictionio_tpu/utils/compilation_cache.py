"""Persistent XLA compilation cache + executable-cache accounting.

No reference analog — the reference's JVM/Spark substrate has no
compilation step, while every first train/eval/serve here pays an XLA
compile (20-40 s for the fused ALS loop on a real TPU). Persisting
compiled executables across processes removes that cost from every run
after the first: `pio train` today, redeploys, repeated tuning sweeps,
and engine-server restarts all reuse yesterday's executables as long as
shapes (bucketed — ops/als.py pack_segments) and the jax/XLA version
match. JAX keys cache entries by program + compile options, so reuse is
always sound.

Layout: ``$PIO_COMPILATION_CACHE_DIR``, default
``$PIO_FS_BASEDIR/compilation_cache`` (beside the localfs/sqlite
storage universe). Set ``PIO_COMPILATION_CACHE_DIR=off`` to disable.

**Executable-cache accounting (the device-observability round).** The
framework's in-memory executable caches — the ALS geometry-bucket
ladder, the retrieval pow2 top-k/width ladder, the serving top-k tiers
— were counted ad hoc (``pio_als_compile_total``) or not at all, and a
compile that happened INSIDE a serving batch (the p99 killer) was
indistinguishable from a deploy-time warm-up compile. Every cache now
reports through :func:`record_executable_compile`:

- ``pio_executable_cache_compiles_total{cache}`` /
  ``…_compile_seconds_total`` count compiles and their wall-clock per
  named cache; ``pio_executable_cache_entries`` /
  ``pio_executable_cache_bytes`` (cache=``persistent``) track the
  on-disk persistent cache (:func:`persistent_cache_stats`).
- Sites that must never compile — a live serving batch, an ingest
  flush — wrap their work in :func:`compile_site`; a compile recorded
  with an ambient site increments ``pio_cold_compiles_total{site}``,
  records a ``compile:<cache>`` span under the ambient trace
  (utils/tracing.py), and lands in the site's drainable event list so
  the serving executor can annotate the batch's ``predict`` span. A
  p99 spike is then attributable to "warm ladder missed width 128"
  straight from ``pio trace``.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import os
import threading
from typing import Dict, Iterator, List, Optional

from predictionio_tpu.utils import metrics as _metrics

logger = logging.getLogger(__name__)

_configured = False


# --- executable-cache accounting ---


def _m_entries() -> "_metrics.Gauge":
    return _metrics.get_registry().gauge(
        "pio_executable_cache_entries",
        "Entries currently held by the persistent on-disk XLA cache "
        "(cache='persistent'; refreshed per scrape from a directory "
        "scan). In-memory ladders report compiles, not held entries — "
        "instance churn would make a held-entries gauge for them lie",
        labels=("cache",),
    )


def _m_compiles() -> "_metrics.Counter":
    return _metrics.get_registry().counter(
        "pio_executable_cache_compiles_total",
        "Executable compiles recorded per named cache (lifetime; "
        "per-instance ladders re-compile after /reload churn, so this "
        "counts work done, not entries held)",
        labels=("cache",),
    )


def _m_compile_seconds() -> "_metrics.Counter":
    return _metrics.get_registry().counter(
        "pio_executable_cache_compile_seconds_total",
        "Cumulative compile wall-clock per named executable cache",
        labels=("cache",),
    )


def _m_cache_bytes() -> "_metrics.Gauge":
    return _metrics.get_registry().gauge(
        "pio_executable_cache_bytes",
        "On-disk bytes of the persistent XLA compilation cache "
        "(cache='persistent'; in-memory caches report entries/seconds "
        "only)",
        labels=("cache",),
    )


def _m_cold() -> "_metrics.Counter":
    return _metrics.get_registry().counter(
        "pio_cold_compiles_total",
        "Compiles that happened inside a latency-critical site (a live "
        "serving batch, an ingest flush) instead of at warm-up — each "
        "one is tail latency a warm ladder should have absorbed",
        labels=("site",),
    )


# the ambient compile site + its per-site event list. The list is the
# hand-off to the serving executor: drain_compile_events() after the
# batch returns the compiles that hit THIS batch, for span annotation.
_SITE: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "pio_compile_site", default=None
)
_SITE_EVENTS: "contextvars.ContextVar[Optional[list]]" = (
    contextvars.ContextVar("pio_compile_events", default=None)
)


@contextlib.contextmanager
def compile_site(site: str) -> Iterator[None]:
    """Declare the enclosed work a latency-critical site: any compile
    recorded inside is a COLD compile attributed to ``site``."""
    t_site = _SITE.set(site)
    t_events = _SITE_EVENTS.set([])
    try:
        yield
    finally:
        _SITE.reset(t_site)
        _SITE_EVENTS.reset(t_events)


def ambient_site() -> Optional[str]:
    return _SITE.get()


def drain_compile_events() -> List[dict]:
    """The cold-compile events recorded under the current
    :func:`compile_site` block so far (and clears them) — the serving
    executor attaches these to the batch's ``predict`` span."""
    events = _SITE_EVENTS.get()
    if not events:
        return []
    drained = list(events)
    del events[:]
    return drained


def record_executable_compile(
    cache: str, seconds: float, key=None
) -> None:
    """Account one freshly compiled executable in the named cache.

    Callers detect the compile themselves (a miss in their own key
    set / dict) and pass the wall-clock their first dispatch took —
    jit tracing+compile runs synchronously on that call, so the
    elapsed time is dominated by the compile. With an ambient
    :func:`compile_site`, the compile is additionally counted cold,
    recorded as a ``compile:<cache>`` span under the ambient trace,
    and appended to the site's drainable event list."""
    _m_compiles().labels(cache=cache).inc()
    _m_compile_seconds().labels(cache=cache).inc(max(0.0, seconds))
    site = _SITE.get()
    if site is None:
        return
    _m_cold().labels(site=site).inc()
    event = {"cache": cache, "seconds": round(seconds, 4), "site": site}
    if key is not None:
        event["key"] = str(key)
    events = _SITE_EVENTS.get()
    if events is not None:
        events.append(event)
    from predictionio_tpu.utils import tracing as _tracing

    ctx = _tracing.current()
    if ctx is not None:
        _tracing.record_span(
            f"compile:{cache}", ctx.trace_id, parent_id=ctx.span_id,
            duration_s=seconds, attrs=dict(event),
        )
    logger.warning(
        "cold compile inside %s: cache=%s key=%s %.3fs",
        site, cache, key, seconds,
    )


@contextlib.contextmanager
def track_compile(cache: str, seen: set, key) -> Iterator[bool]:
    """The one-liner for executable caches keyed by hashable statics:
    yields whether ``key`` is NEW in ``seen`` (a compile is about to
    happen on the enclosed first dispatch) and records it on success.
    A dispatch that RAISES un-marks the key and records nothing — the
    executable was never cached, and the retry that performs the real
    compile must still be attributable. ``seen`` mutates under the
    module lock, so concurrent first calls record the compile once."""
    import time as _time

    with _TRACK_LOCK:
        new = key not in seen
        if new:
            seen.add(key)
    t0 = _time.perf_counter()
    try:
        yield new
    except BaseException:
        if new:
            with _TRACK_LOCK:
                seen.discard(key)
        raise
    else:
        if new:
            record_executable_compile(
                cache, _time.perf_counter() - t0, key=key
            )


_TRACK_LOCK = threading.Lock()


def persistent_cache_stats() -> Dict[str, int]:
    """Entry count and on-disk bytes of the persistent XLA cache dir
    (zeros when disabled); sets the ``cache='persistent'`` gauges."""
    path = ensure_compilation_cache()
    entries = 0
    total = 0
    if path and os.path.isdir(path):
        try:
            for name in os.listdir(path):
                full = os.path.join(path, name)
                if os.path.isfile(full):
                    entries += 1
                    total += os.path.getsize(full)
        except OSError:
            logger.debug("persistent cache scan failed", exc_info=True)
    _m_entries().labels(cache="persistent").set(float(entries))
    _m_cache_bytes().labels(cache="persistent").set(float(total))
    return {"entries": entries, "bytes": total}


def ensure_compilation_cache() -> Optional[str]:
    """Point JAX at the persistent cache directory (idempotent; best
    effort — failures log and fall back to in-memory-only caching).
    Returns the directory in use, or None when disabled/failed."""
    global _configured
    if _configured:
        import jax

        return jax.config.jax_compilation_cache_dir or None
    _configured = True
    path = os.environ.get("PIO_COMPILATION_CACHE_DIR")
    if path is not None and path.lower() in ("off", "none", "0", ""):
        return None
    if path is None:
        from predictionio_tpu.utils.fs import fs_basedir

        path = os.path.join(fs_basedir(), "compilation_cache")
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        # thresholds first: if any knob is missing on this jax version we
        # bail out BEFORE activating the on-disk cache, so a None return
        # is never half-configured.
        # Cache every program the framework compiles — the default 1 s
        # floor would skip the small serving/predict executables whose
        # cold compiles are exactly the deploy-time tail latency the
        # warm-up hook exists to hide.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        # bound on-disk growth (LRU eviction): tuning sweeps and jax/XLA
        # version bumps would otherwise accumulate entries forever
        jax.config.update("jax_compilation_cache_max_size", 4 * 1024**3)
        jax.config.update("jax_compilation_cache_dir", path)
        logger.info("XLA compilation cache at %s", path)
        return path
    except Exception as e:  # unwritable dir, old jax — never fatal
        try:
            import jax

            jax.config.update("jax_compilation_cache_dir", "")
        except Exception:
            pass
        logger.warning("compilation cache disabled: %s", e)
        return None
