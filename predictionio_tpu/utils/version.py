"""Version parsing/comparison shared by the template min-version gate
(tools/template.py, reference Template.scala:417-429) and the upgrade
check (tools/upgrade.py, reference WorkflowUtils.scala:386-406)."""

from __future__ import annotations

import re
from typing import Tuple


def parse_version(v: str) -> Tuple[int, ...]:
    """Leading-digit numeric components: '0.9.3-SNAPSHOT' -> (0, 9, 3),
    '0rc1' components parse as their leading digits."""
    out = []
    for part in v.split("."):
        m = re.match(r"\d+", part)
        out.append(int(m.group()) if m else 0)
    return tuple(out)


def _padded(a: str, b: str) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    ta, tb = parse_version(a), parse_version(b)
    width = max(len(ta), len(tb))
    return (
        ta + (0,) * (width - len(ta)),
        tb + (0,) * (width - len(tb)),
    )


def version_lt(a: str, b: str) -> bool:
    """True when a < b, comparing width-normalized numeric components so
    '1.0' == '1.0.0' (not less-than)."""
    ta, tb = _padded(a, b)
    return ta < tb


def version_gte(a: str, b: str) -> bool:
    ta, tb = _padded(a, b)
    return ta >= tb
