"""HBM residency ledger: exact per-component device-memory accounting.

The capacity items on the ROADMAP (10M-item quantized catalogs, the
device-resident incremental pack) all hinge on knowing EXACTLY what is
parked in HBM and by whom — the ALX serving recipe (arXiv:2112.02194)
keeps factors resident between queries, and the approximate-MF work
(arXiv:1808.03843) makes bytes-per-item the scaling ceiling. Before
this module, resident bytes were tracked only for the retriever
(``pio_retrieval_resident_bytes``); every other residency — retained-LRU
prepared serving states, replicated ServingFactors uploads, live train
factor state, the pack cache's host wires — was invisible, which is how
the PR 13 leak class (a displaced instance whose buffers never freed)
could only be found by reading code.

Every component that parks buffers on device registers its allocations
through this process-global ledger:

- :meth:`DeviceLedger.register` returns a :class:`LedgerHandle` the
  component updates (``set``/``add``) and closes when the buffers are
  released. Passing ``anchor=obj`` arms a ``weakref.finalize`` so a
  component dropped without an explicit close still zeroes its entry
  when the owning object is collected (refcount-freed buffers stay
  truthful).
- Exposed as ``pio_device_ledger_bytes{device,component,owner}``:
  ``device`` is the jax device (or span) the bytes live on — ``host``
  for host-RAM residency like the pack cache — and ``owner`` is the
  engine-instance id when the allocation happened under a
  :class:`LedgerScope` (the DeployedEngine lifecycle), ``-`` otherwise.
- :meth:`DeviceLedger.reconcile` diffs the ledger's per-device totals
  against ``device.memory_stats()`` into
  ``pio_device_ledger_drift_bytes{device}`` — untracked growth (a leak)
  is itself a metric. Backends without memory stats (XLA CPU) skip the
  drift gauge unless a probe is injected (tests).
- :meth:`LedgerScope.check_released` is the promotion pipeline's
  monitored invariant: after a displaced instance's ``release()``, its
  scope's bytes must be zero; a nonzero remainder increments
  ``pio_device_ledger_leaks_total{component}`` and logs — the PR 13
  leak class, now a metric instead of an archaeology project.

Like utils/metrics.py and utils/tracing.py, this module is a sanctioned
home for module-level observability state (the single process-global
ledger); tests/test_lint.py's device-residency lint polices that new
long-lived device placements under ops/ and api/ register here.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import threading
import weakref
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from predictionio_tpu.utils import metrics as _metrics

logger = logging.getLogger(__name__)

__all__ = [
    "DeviceLedger",
    "LedgerHandle",
    "LedgerScope",
    "get_ledger",
    "device_label_of",
    "HOST_DEVICE",
    "UNOWNED",
]

# the ledger's label for host-RAM residency (the pack cache's wires):
# excluded from drift reconciliation, which only matches jax devices
HOST_DEVICE = "host"
# the owner label of allocations made outside any LedgerScope
UNOWNED = "-"


def _m_bytes() -> "_metrics.Gauge":
    return _metrics.get_registry().gauge(
        "pio_device_ledger_bytes",
        "Bytes of long-lived buffers registered in the HBM residency "
        "ledger, by device, component, and owning engine-instance "
        "('-' = unowned)",
        labels=("device", "component", "owner"),
    )


def _m_drift() -> "_metrics.Gauge":
    return _metrics.get_registry().gauge(
        "pio_device_ledger_drift_bytes",
        "device.memory_stats() bytes_in_use minus the ledger's total "
        "for that device — sustained positive drift is untracked "
        "residency (a leak); unavailable on backends without memory "
        "stats",
        labels=("device",),
    )


def _m_leaks() -> "_metrics.Counter":
    return _metrics.get_registry().counter(
        "pio_device_ledger_leaks_total",
        "Release-invariant violations: a displaced instance whose "
        "ledger bytes were still nonzero after release_serving ran "
        "(the PR 13 leak class, per component)",
        labels=("component",),
    )


def device_label_of(x) -> str:
    """The ledger device label for a jax array: its device's string, or
    ``<first device>x<N>`` for an array sharded over N devices. Host
    numpy (or anything without ``.devices()``) labels :data:`HOST_DEVICE`.
    """
    devices = getattr(x, "devices", None)
    if devices is None:
        return HOST_DEVICE
    try:
        labels = sorted(str(d) for d in devices())
    except Exception:  # a freed/donated buffer — best-effort label
        return "unknown"
    if not labels:
        return "unknown"
    if len(labels) == 1:
        return labels[0]
    return f"{labels[0]}x{len(labels)}"


def device_footprint(*arrays) -> "Tuple[str, int, Dict[str, int]]":
    """``(label, total_physical_bytes, per-device bytes)`` for a set of
    jax arrays, computed from their addressable shards — so a
    row-SHARDED matrix attributes each shard's bytes to its own device
    and a REPLICATED one counts every per-device copy (``.nbytes``
    alone is the logical size: one copy). The per-device map is what
    :meth:`DeviceLedger.reconcile` diffs against each device's
    ``memory_stats()``; without it, mesh deployments would show the
    whole resident set as false drift. Host numpy contributes under
    :data:`HOST_DEVICE`."""
    members: Dict[str, int] = {}
    for x in arrays:
        shards = getattr(x, "addressable_shards", None)
        counted = False
        if shards is not None:
            try:
                for sh in shards:
                    lbl = str(sh.device)
                    members[lbl] = members.get(lbl, 0) + int(
                        sh.data.nbytes
                    )
                counted = True
            except Exception:  # exotic array types — fall through
                logger.debug("shard walk failed", exc_info=True)
        if not counted:
            lbl = device_label_of(x)
            members[lbl] = members.get(lbl, 0) + int(
                getattr(x, "nbytes", 0) or 0
            )
    total = sum(members.values())
    if not members:
        return HOST_DEVICE, 0, {}
    if len(members) == 1:
        return next(iter(members)), total, members
    first = sorted(members)[0]
    return f"{first}x{len(members)}", total, members


class Anchor:
    """A throwaway weakref-able object: ``register(anchor=Anchor())``
    held in a local ties a handle's lifetime to the enclosing frame —
    the handle closes when the frame exits (including by exception)
    without try/finally plumbing through a long function body."""

    __slots__ = ("__weakref__",)


class LedgerHandle:
    """One component's live residency entry. Thread-safe via the owning
    ledger's lock; ``close()`` is idempotent (explicit close and the
    ``anchor`` finalizer may both fire)."""

    __slots__ = (
        "_ledger", "component", "device", "owner", "_nbytes", "_closed",
        "_members", "__weakref__",
    )

    def __init__(self, ledger: "DeviceLedger", component: str, device: str,
                 owner: str, nbytes: int,
                 members: Optional[Dict[str, int]] = None):
        self._ledger = ledger
        self.component = component
        self.device = device
        self.owner = owner
        self._nbytes = int(max(0, nbytes))
        # physical bytes per individual device (reconcile's view of a
        # sharded/replicated entry); a plain registration is all on its
        # one device label
        self._members: Dict[str, int] = (
            dict(members) if members else {self.device: self._nbytes}
        )
        self._closed = False

    @property
    def nbytes(self) -> int:
        return 0 if self._closed else self._nbytes

    @property
    def closed(self) -> bool:
        return self._closed

    def set(
        self, nbytes: int, members: Optional[Dict[str, int]] = None
    ) -> None:
        """Replace this entry's byte count (a mask re-upload, a
        resize). Without an explicit per-device ``members`` map the old
        one rescales proportionally — right for a same-layout
        re-upload; pass a fresh :func:`device_footprint` map when the
        sharding changed."""
        self._ledger._update(self, int(max(0, nbytes)), members)

    def add(self, nbytes: int) -> None:
        self._ledger._update(self, self._nbytes + int(nbytes), None)

    def close(self) -> None:
        """Zero and retire the entry (the buffers were released — or
        will free by refcount; the ledger records registered residency
        INTENT, so a straggler batch still holding freed-by-owner
        buffers reads as drift, not as ledger bytes)."""
        self._ledger._close(self)


class LedgerScope:
    """Groups the handles registered during one owner's lifecycle (a
    DeployedEngine's prepare/warm) so release can assert THEM — and only
    them — reached zero, even when a same-version twin is also resident.
    The scope ``label`` (the engine-instance id) becomes the handles'
    ``owner`` gauge label."""

    def __init__(self, ledger: "DeviceLedger", label: str):
        self._ledger = ledger
        self.label = str(label or UNOWNED)
        self._handles: List[LedgerHandle] = []
        self._lock = threading.Lock()

    def _adopt(self, handle: LedgerHandle) -> None:
        with self._lock:
            self._handles.append(handle)

    @contextlib.contextmanager
    def activate(self) -> Iterator["LedgerScope"]:
        """Bind this scope as the ambient registration target: handles
        registered inside the block join the scope and carry its label
        as their ``owner``."""
        token = _ACTIVE_SCOPE.set(self)
        try:
            yield self
        finally:
            _ACTIVE_SCOPE.reset(token)

    def bytes(self) -> int:
        with self._lock:
            return sum(h.nbytes for h in self._handles)

    def check_released(self) -> int:
        """The release invariant: returns the bytes still registered
        under this scope (0 = clean). Nonzero increments
        ``pio_device_ledger_leaks_total`` per leaking component and
        logs — the displaced instance did not free everything it
        registered."""
        with self._lock:
            open_handles = [h for h in self._handles if h.nbytes > 0]
        leaked = sum(h.nbytes for h in open_handles)
        if leaked:
            leaks = _m_leaks()
            for h in open_handles:
                leaks.labels(component=h.component).inc()
            logger.warning(
                "device-ledger release invariant violated for owner %s: "
                "%d bytes still registered (%s)",
                self.label, leaked,
                ", ".join(
                    f"{h.component}@{h.device}={h.nbytes}"
                    for h in open_handles
                ),
            )
        return leaked


_ACTIVE_SCOPE: "contextvars.ContextVar[Optional[LedgerScope]]" = (
    contextvars.ContextVar("pio_ledger_scope", default=None)
)


class DeviceLedger:
    """The process-global residency registry. All mutation funnels
    through the instance lock; gauge children are re-summed per
    (device, component, owner) key on every mutation — entries are few
    (one per resident component instance), so this is far off any hot
    path."""

    def __init__(self):
        self._lock = threading.Lock()
        self._handles: "Dict[LedgerHandle, None]" = {}

    # -- registration --

    def register(
        self,
        component: str,
        nbytes: int = 0,
        device: str = HOST_DEVICE,
        anchor=None,
        members: Optional[Dict[str, int]] = None,
    ) -> LedgerHandle:
        """Register one component's residency. ``device`` is the label
        from :func:`device_label_of` (or :data:`HOST_DEVICE`);
        ``members`` is the per-device physical-byte map from
        :func:`device_footprint` — REQUIRED for correct drift
        reconciliation of sharded/replicated entries (omitted, all
        bytes attribute to ``device``). The ambient
        :class:`LedgerScope` (if any) adopts the handle and stamps its
        ``owner``. ``anchor`` arms a finalizer that closes the handle
        when the object is collected — the backstop for refcount-freed
        device state that never saw an explicit close."""
        scope = _ACTIVE_SCOPE.get()
        owner = scope.label if scope is not None else UNOWNED
        handle = LedgerHandle(
            self, component, str(device), owner, nbytes, members=members
        )
        with self._lock:
            self._handles[handle] = None
        if scope is not None:
            scope._adopt(handle)
        self._publish(handle.device, handle.component, handle.owner)
        if anchor is not None:
            weakref.finalize(anchor, handle.close)
        return handle

    def scope(self, label: str) -> LedgerScope:
        return LedgerScope(self, label)

    # -- handle callbacks --

    def _update(
        self,
        handle: LedgerHandle,
        nbytes: int,
        members: Optional[Dict[str, int]] = None,
    ) -> None:
        with self._lock:
            if handle._closed:
                return
            if members is not None:
                handle._members = dict(members)
            else:
                old_total = sum(handle._members.values())
                if old_total > 0:
                    handle._members = {
                        k: int(round(v * nbytes / old_total))
                        for k, v in handle._members.items()
                    }
                else:
                    handle._members = {handle.device: nbytes}
            handle._nbytes = nbytes
        self._publish(handle.device, handle.component, handle.owner)

    def _close(self, handle: LedgerHandle) -> None:
        with self._lock:
            if handle._closed:
                return
            handle._closed = True
            self._handles.pop(handle, None)
        self._publish(handle.device, handle.component, handle.owner)

    def _publish(self, device: str, component: str, owner: str) -> None:
        with self._lock:
            total = sum(
                h._nbytes
                for h in self._handles
                if not h._closed
                and h.device == device
                and h.component == component
                and h.owner == owner
            )
        _m_bytes().labels(
            device=device, component=component, owner=owner
        ).set(float(total))

    # -- queries --

    def _live(self) -> List[LedgerHandle]:
        with self._lock:
            return [h for h in self._handles if not h._closed]

    def total_bytes(
        self,
        component: Optional[str] = None,
        device: Optional[str] = None,
        owner: Optional[str] = None,
    ) -> int:
        return sum(
            h.nbytes
            for h in self._live()
            if (component is None or h.component == component)
            and (device is None or h.device == device)
            and (owner is None or h.owner == owner)
        )

    def owner_bytes(self, owner: str) -> int:
        return self.total_bytes(owner=owner)

    def breakdown(self) -> Dict[str, Dict[str, int]]:
        """``{device: {component: bytes}}`` — the detail view `pio top`
        and the collector's fleet.json render."""
        out: Dict[str, Dict[str, int]] = {}
        for h in self._live():
            per = out.setdefault(h.device, {})
            per[h.component] = per.get(h.component, 0) + h.nbytes
        return out

    # -- drift reconciliation --

    def reconcile(
        self, probe: Optional[Callable] = None
    ) -> Dict[str, dict]:
        """Diff the ledger against the devices' own accounting.

        ``probe(device) -> Optional[int]`` returns bytes-in-use for one
        jax device (None = unavailable); the default reads
        ``device.memory_stats()``. Devices without stats (XLA CPU)
        contribute no drift sample. The ledger side of each diff is the
        sum of the handles' PER-DEVICE member maps
        (:func:`device_footprint`), so a sharded/replicated entry
        attributes each device's actual shard/copy bytes to that
        device — a healthy mesh deployment reconciles to ~zero drift
        instead of flagging its whole resident set. Entries on labels
        that match no local device (``host``) are reported under their
        own label with ``in_use=None``. Sets
        ``pio_device_ledger_drift_bytes{device}`` per probed device and
        returns ``{device: {"ledger", "in_use", "drift"}}``."""
        if probe is None:
            probe = _default_probe
        per_device: Dict[str, int] = {}
        for h in self._live():
            with self._lock:
                members = dict(h._members)
            for lbl, b in members.items():
                per_device[lbl] = per_device.get(lbl, 0) + b
        report: Dict[str, dict] = {}
        try:
            import jax

            devices = list(jax.local_devices())
        except Exception:  # jax unavailable/broken: ledger-only view
            devices = []
        probed = set()
        for dev in devices:
            label = str(dev)
            probed.add(label)
            try:
                in_use = probe(dev)
            except Exception:
                in_use = None
            ledger = per_device.get(label, 0)
            entry: dict = {"ledger": ledger, "in_use": in_use}
            if in_use is not None:
                drift = int(in_use) - ledger
                entry["drift"] = drift
                _m_drift().labels(device=label).set(float(drift))
            else:
                entry["drift"] = None
            report[label] = entry
        for label, ledger in per_device.items():
            if label not in probed:
                report[label] = {
                    "ledger": ledger, "in_use": None, "drift": None,
                }
        return report


def _default_probe(device) -> Optional[int]:
    stats = getattr(device, "memory_stats", lambda: None)()
    if not stats:
        return None
    value = stats.get("bytes_in_use")
    return int(value) if value is not None else None


# THE process-global ledger (one per worker process, like the metrics
# registry it records into).
LEDGER = DeviceLedger()


def get_ledger() -> DeviceLedger:
    return LEDGER
