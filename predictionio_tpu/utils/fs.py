"""Filesystem root shared by every disk-touching subsystem."""

from __future__ import annotations

import os


def fs_basedir() -> str:
    """The framework's on-disk root (``PIO_FS_BASEDIR``, default
    ``~/.predictionio_tpu``) — sqlite/localfs storage, persistent models,
    and the XLA compilation cache all live under it (reference
    ``PIO_FS_BASEDIR``, conf/pio-env.sh.template)."""
    return os.environ.get(
        "PIO_FS_BASEDIR", os.path.expanduser("~/.predictionio_tpu")
    )
