"""Shared utilities: serialization, logging, timers."""
