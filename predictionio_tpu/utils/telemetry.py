"""Fleet-wide telemetry plane: metrics federation, trace stitching, SLOs.

PRs 13–14 turned the system into a real fleet — SO_REUSEPORT serving
workers under a supervisor, N replicated storage-gateway nodes, a
continuous retrain→swap loop — but observability stayed per-process:
spans lived in each process's bounded ring, ``/metrics`` had to be
scraped and merged by hand per target, nothing retained history, and
the promotion observation window judged rollback from the one process
it could see. This module is the collector tier that closes the gap
(the multi-host serving deployment shape of the ALX paper,
arXiv:2112.02194, is what it targets; the reference delegated all of
this to external dashboards):

- **Federated metrics.** The :class:`Collector` polls every fleet
  process's existing ``/metrics`` endpoint into a bounded per-target
  ring of timestamped exposition snapshots, and merges the LATEST
  snapshots exactly: counters and cumulative histogram bucket vectors
  SUM across targets (PR 6's fixed-bucket invariant — a fleet's merged
  p99 equals a single combined worker's), while gauges keep per-target
  identity via an added ``instance`` label so `pio_host_rss_bytes`
  from three workers never falsely sums into one number.
- **Cross-process trace stitching.** Spans are pulled INCREMENTALLY
  from every target's ring (``/debug/traces.json?since=<seq>``, the
  per-process span-sequence cursor in utils/tracing.py) and joined by
  trace id into one tree — the ``X-PIO-Trace-Id``/``X-PIO-Parent-Span``
  chain already crosses http→batch→predict→feedback→ingest→gateway→
  committer, so one user request finally renders as ONE end-to-end
  trace across the engine worker, the event server, and the cluster
  node that committed the write.
- **SLO burn-rate engine.** Declarative SLOs (serving availability,
  serving latency, ingest error rate) are evaluated over fast/slow
  windows from the retention ring — the standard multiwindow
  burn-rate method: ``burn = bad_fraction / error_budget``; an alert
  fires only when BOTH windows burn above threshold (fast-only =
  blips, slow-only = a fire that already ended). Exposed as
  ``pio_slo_burn_rate{slo,window}`` + ``pio_slo_alert{slo}`` and the
  collector's ``/api/alerts.json``; the PR 13 promotion observation
  window can consult the collector (``--promote-collector-url``) for
  the FLEET-wide post-swap error rate instead of one process's
  counters.

The HTTP daemon wrapping this class lives in ``tools/collector.py``
(``pio collector``); this module is transport-free so tests and the
promotion pipeline can drive a Collector in-process.

Like utils/metrics.py and utils/tracing.py, the collector records its
own operational families (``pio_collector_*``) into the process-global
registry — a collector is itself a scrapable fleet member.
"""

from __future__ import annotations

import collections
import concurrent.futures
import dataclasses
import json
import logging
import math
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple

from predictionio_tpu.utils import health as _health
from predictionio_tpu.utils import metrics as _metrics

logger = logging.getLogger(__name__)

__all__ = [
    "Collector",
    "SLODef",
    "default_slos",
    "load_slos",
    "DEFAULT_POLL_INTERVAL_S",
    "DEFAULT_RETENTION",
    "DEFAULT_SPAN_RETENTION",
]

# snapshots kept per target: at the default 2 s poll interval this is
# ~12 minutes of history — enough for a 5-minute fast window plus slack;
# size the ring to cover the SLOW window for full-fidelity slow burns
# (docs/OBSERVABILITY.md's sizing table)
DEFAULT_RETENTION = 360
DEFAULT_POLL_INTERVAL_S = 2.0
# stitched spans kept collector-wide (each target's own ring holds 4096)
DEFAULT_SPAN_RETENTION = 32768

# the collector's poll loop heartbeat: a wedged scrape sweep (every
# target timing out serially) must degrade the collector's /readyz
COLLECTOR_DEADLINE_S = 120.0

# |ledger drift| beyond this on any target trips pio_ledger_drift_alert
# (docs/OBSERVABILITY.md, device-plane section). Sized well above the
# allocator slack/workspace noise a healthy serving process shows, far
# below a leaked factor matrix.
DRIFT_ALERT_BYTES = 256 * 1024 * 1024


# --- SLO declarations ---

SLO_KINDS = ("availability", "latency", "ingest_error_rate")


@dataclasses.dataclass(frozen=True)
class SLODef:
    """One declarative SLO evaluated from the retention ring.

    ``objective`` is the GOOD fraction target (0.999 = "99.9% of
    serving requests succeed"); the error budget is ``1 - objective``
    and ``burn_rate = bad_fraction / error_budget`` — burn 1.0 spends
    the budget exactly at the objective's natural rate, burn 14.4 (the
    classic fast-page threshold) exhausts a 30-day budget in ~2 days.

    Kinds:

    - ``availability``: bad = engine-server 5xx ∕ (serving requests
      + those 5xx), from ``pio_http_errors_total`` and
      ``pio_serving_requests_total`` window deltas;
    - ``latency``: bad = serving requests slower than
      ``latency_threshold_s``, from ``pio_serving_latency_seconds``
      bucket deltas (the threshold is clamped UP to the nearest fixed
      bucket bound — a threshold past the largest finite bound clamps
      DOWN to it — so the fraction is exact, never interpolated; the
      default 0.2048 IS a bound of the fixed ladder, so the declared
      and enforced thresholds coincide);
    - ``ingest_error_rate``: bad = event-server 5xx ∕ (ingested events
      + those 5xx).
    """

    name: str
    kind: str
    objective: float = 0.999
    # a bound of LATENCY_BUCKETS_S (1e-4 x 2^11), so the enforced
    # threshold equals the declared one with no clamping surprise
    latency_threshold_s: float = 0.2048
    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    burn_threshold: float = 14.4

    def __post_init__(self):
        if self.kind not in SLO_KINDS:
            raise ValueError(
                f"unknown SLO kind {self.kind!r} (expected one of "
                f"{SLO_KINDS})"
            )
        if not (0.0 < self.objective < 1.0):
            raise ValueError("SLO objective must be in (0, 1)")
        if self.fast_window_s <= 0 or self.slow_window_s < self.fast_window_s:
            raise ValueError("need 0 < fast_window_s <= slow_window_s")


def default_slos() -> Tuple[SLODef, ...]:
    """The stock fleet SLOs (docs/OBSERVABILITY.md documents each)."""
    return (
        SLODef(name="serving-availability", kind="availability",
               objective=0.999),
        SLODef(name="serving-latency", kind="latency", objective=0.99,
               latency_threshold_s=0.2048),
        SLODef(name="ingest-errors", kind="ingest_error_rate",
               objective=0.999),
    )


def load_slos(path: str) -> Tuple[SLODef, ...]:
    """Load SLO declarations from a JSON file: a list of objects whose
    keys are :class:`SLODef` fields (``name`` and ``kind`` required)."""
    with open(path, "r", encoding="utf-8") as f:
        raw = json.load(f)
    if not isinstance(raw, list):
        raise ValueError("SLO file must hold a JSON list of objects")
    out = []
    valid = {f.name for f in dataclasses.fields(SLODef)}
    for i, item in enumerate(raw):
        if not isinstance(item, dict):
            raise ValueError(f"SLO entry {i} is not an object")
        unknown = set(item) - valid
        if unknown:
            raise ValueError(
                f"SLO entry {i} has unknown keys {sorted(unknown)}"
            )
        out.append(SLODef(**item))
    return tuple(out)


# --- per-target state ---


def _instance_label(url: str) -> str:
    """``http://host:7070`` → ``host:7070`` — the ``instance`` label
    value federated gauges carry (mirrors Prometheus's convention)."""
    parsed = urllib.parse.urlsplit(url if "//" in url else f"//{url}")
    label = parsed.netloc or url
    if parsed.path and parsed.path != "/":
        label += parsed.path
    return label


class _TargetState:
    """One fleet process under observation: its snapshot ring, span
    cursor, and last health/readiness verdicts. All mutation happens on
    the collector's poll thread; readers take the collector lock."""

    def __init__(self, url: str, retention: int):
        self.url = url.rstrip("/")
        self.instance = _instance_label(self.url)
        # (wall-clock seconds, flat parse_exposition samples)
        self.ring: "collections.deque" = collections.deque(maxlen=retention)
        # typed families of the NEWEST snapshot only (federation input)
        self.families: Optional[Dict[str, dict]] = None
        self.span_cursor = 0
        self.up = False
        self.ready: Optional[bool] = None
        self.last_error: Optional[str] = None
        self.last_scrape_s: Optional[float] = None
        self.health: Optional[dict] = None

    def sample_at(self, cutoff: float) -> Optional[Tuple[float, Dict]]:
        """Newest ring entry at-or-before ``cutoff`` (else the oldest
        entry — a short ring degrades to "since retention began")."""
        chosen = None
        for entry in self.ring:
            if entry[0] <= cutoff:
                chosen = entry
            else:
                break
        if chosen is None and self.ring:
            chosen = self.ring[0]
        return chosen

    def latest(self) -> Optional[Tuple[float, Dict]]:
        return self.ring[-1] if self.ring else None


def _delta_samples(
    newer: Dict[str, float], older: Dict[str, float]
) -> Dict[str, float]:
    """Per-sample counter deltas, clamped at 0 (a process restart resets
    its counters; a negative delta must read as "fresh start", never as
    negative traffic)."""
    return {
        key: max(0.0, value - older.get(key, 0.0))
        for key, value in newer.items()
    }


# --- the collector ---


@dataclasses.dataclass
class _ExperimentEntry:
    """One registered experiment: its spec, the per-target cumulative
    baseline captured at registration, and (once reached) the sticky
    final report."""

    spec: object
    registered_s: float
    baselines: Dict[str, Dict[str, float]]
    final: Optional[dict] = None


class Collector:
    """Poll a fleet's existing public endpoints; serve the merged view.

    ``targets`` are base URLs (event servers, engine workers, storage
    gateways, cluster nodes — any process exposing ``/metrics``).
    ``access_key``/``secret`` are forwarded on the span pull
    (``/debug/traces.json`` is gated per server: accessKey on the event
    and engine servers, the shared secret on gateways); metrics and
    health endpoints are unauthenticated by design. All state is
    instance-scoped — tests run several collectors in one process.
    """

    def __init__(
        self,
        targets: Sequence[str] = (),
        *,
        poll_interval_s: float = DEFAULT_POLL_INTERVAL_S,
        retention: int = DEFAULT_RETENTION,
        span_retention: int = DEFAULT_SPAN_RETENTION,
        slos: Optional[Sequence[SLODef]] = None,
        access_key: str = "",
        secret: str = "",
        timeout_s: float = 5.0,
    ):
        self.poll_interval_s = float(poll_interval_s)
        self.retention = max(2, int(retention))
        self.span_retention = max(1, int(span_retention))
        self.slos: Tuple[SLODef, ...] = tuple(
            default_slos() if slos is None else slos
        )
        names = [s.name for s in self.slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names in {names}")
        # the multiwindow blip filter is void when the ring cannot
        # cover the slow window (both windows then degrade to "since
        # retention began" and measure roughly the same span) — warn
        # loudly instead of silently paging on transients
        slowest = max((s.slow_window_s for s in self.slos), default=0.0)
        covered = self.retention * self.poll_interval_s
        if slowest and covered < slowest:
            logger.warning(
                "collector retention (%d snapshots x %.1fs = %.0fs) "
                "does not cover the slowest SLO window (%.0fs): slow "
                "burns degrade toward the fast window and the "
                "multiwindow alert filter loses its blip suppression; "
                "raise retention to at least %d",
                self.retention, self.poll_interval_s, covered, slowest,
                math.ceil(slowest / self.poll_interval_s),
            )
        self.access_key = access_key
        self.secret = secret
        self.timeout_s = float(timeout_s)
        self._lock = threading.RLock()
        self._targets: "Dict[str, _TargetState]" = {}
        # stitched spans, fleet-wide: a bounded deque + a dedup key set
        # ((instance, traceId, spanId) — span seqs reset on process
        # restart, span ids do not collide within a trace)
        self._spans: "collections.deque" = collections.deque()
        self._span_keys: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_alerts: List[dict] = []
        self._last_slo_report: List[dict] = []
        # experimentation plane: registered ExperimentSpecs plus, per
        # experiment, the cumulative-counter baseline captured at
        # registration (per target, so a restarted worker's counter
        # reset clamps to zero instead of going negative) and — once the
        # sequential test crosses its boundary — the STICKY final
        # report: an always-valid test's verdict is a stopping rule, a
        # later fluctuation must not un-decide it.
        self._experiments: Dict[str, _ExperimentEntry] = {}
        self._last_experiments: Dict[str, dict] = {}
        reg = _metrics.get_registry()
        self._m_scrapes = reg.counter(
            "pio_collector_scrapes_total",
            "Collector target scrapes by outcome (ok / error = the "
            "/metrics fetch failed; the target renders DOWN)",
            labels=("target", "outcome"),
        )
        self._m_scrape_seconds = reg.histogram(
            "pio_collector_scrape_seconds",
            "Wall clock of one full target scrape (metrics + health + "
            "incremental span pull)",
            buckets=_metrics.LATENCY_BUCKETS_S,
        )
        self._m_targets = reg.gauge(
            "pio_collector_targets",
            "Fleet targets registered with this collector",
        )
        self._m_spans = reg.counter(
            "pio_collector_spans_total",
            "Spans pulled incrementally from fleet targets",
            labels=("target",),
        )
        self._m_burn = reg.gauge(
            "pio_slo_burn_rate",
            "SLO error-budget burn rate per evaluation window "
            "(bad_fraction / error_budget; 1.0 = burning exactly at "
            "the objective's natural rate)",
            labels=("slo", "window"),
        )
        self._m_alert = reg.gauge(
            "pio_slo_alert",
            "1 while an SLO's fast AND slow windows both burn above "
            "its threshold (the multiwindow page condition)",
            labels=("slo",),
        )
        # the stock SLO-adjacent device-ledger gauges: fleet-wide
        # registered residency, and a drift alert when any target's
        # ledger-vs-memory_stats drift exceeds the threshold (untracked
        # HBM growth — the leak signal)
        self._m_fleet_ledger = reg.gauge(
            "pio_fleet_ledger_bytes",
            "Registered HBM-ledger residency summed across the fleet's "
            "latest scrapes",
        )
        self._m_drift_alert = reg.gauge(
            "pio_ledger_drift_alert",
            "1 while any fleet target's |pio_device_ledger_drift_bytes| "
            "exceeds the collector's drift threshold (untracked device "
            "residency — the leak signal)",
        )
        # experimentation gauges, evaluated in the ring the way SLO
        # burns are: one peek per poll tick, licensed by the test being
        # always-valid
        self._m_exp_lambda = reg.gauge(
            "pio_experiment_log_lambda",
            "mSPRT log likelihood ratio of the arm's attributed "
            "hit-rate vs control (crosses ln(1/alpha) exactly once, at "
            "the verdict)",
            labels=("experiment", "variant"),
        )
        self._m_exp_rate = reg.gauge(
            "pio_experiment_hit_rate",
            "Attributed hit-rate per experiment arm since the "
            "experiment registered with this collector",
            labels=("experiment", "variant"),
        )
        self._m_exp_p99 = reg.gauge(
            "pio_experiment_p99_seconds",
            "Windowed serving p99 per experiment arm (the latency "
            "guardrail input)",
            labels=("experiment", "variant"),
        )
        self._m_exp_decided = reg.gauge(
            "pio_experiment_decided",
            "1 once the experiment's sequential test has a verdict (or "
            "its horizon passed), 0 while running",
            labels=("experiment",),
        )
        for url in targets:
            self.add_target(url)

    # -- target registry --

    def add_target(self, url: str) -> bool:
        """Register one fleet process (idempotent — re-registration by
        a restarted supervisor is a no-op). Returns True when new."""
        url = (url or "").rstrip("/")
        if not url:
            raise ValueError("empty target URL")
        if "://" not in url:
            url = "http://" + url
        with self._lock:
            if url in self._targets:
                return False
            self._targets[url] = _TargetState(url, self.retention)
            self._m_targets.set(float(len(self._targets)))
        logger.info("collector: registered target %s", url)
        return True

    def remove_target(self, url: str) -> bool:
        url = (url or "").rstrip("/")
        if "://" not in url:
            url = "http://" + url
        with self._lock:
            removed = self._targets.pop(url, None) is not None
            self._m_targets.set(float(len(self._targets)))
        return removed

    def target_urls(self) -> List[str]:
        with self._lock:
            return sorted(self._targets)

    def _states(self) -> List[_TargetState]:
        with self._lock:
            return [self._targets[u] for u in sorted(self._targets)]

    # -- polling --

    def _fetch(self, url: str) -> bytes:
        with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
            return resp.read()

    def _poll_target(self, state: _TargetState) -> None:
        t0 = time.perf_counter()
        now = time.time()
        try:
            text = self._fetch(state.url + "/metrics").decode("utf-8")
        except Exception as e:
            with self._lock:
                state.up = False
                state.ready = None
                state.last_error = f"{type(e).__name__}: {e}"
            self._m_scrapes.labels(
                target=state.instance, outcome="error"
            ).inc()
            return
        samples = _metrics.parse_exposition(text)
        families = _metrics.parse_exposition_families(text)
        health: Optional[dict] = None
        ready: Optional[bool] = None
        try:
            health = json.loads(
                self._fetch(state.url + "/healthz").decode("utf-8")
            )
        except Exception:
            logger.debug(
                "collector: healthz fetch from %s failed", state.url,
                exc_info=True,
            )
        try:
            self._fetch(state.url + "/readyz")
            ready = True
        except urllib.error.HTTPError:
            ready = False
        except Exception:
            ready = None
        # restart detection the span-sequence comparison alone cannot
        # provide: a restarted process that recorded MORE spans than
        # the cursor before our next poll presents a high-water mark
        # ABOVE it, silently hiding its early spans. Uptime going
        # backwards is unambiguous — drop the cursor so the pull below
        # starts from scratch (the stitched-store dedup absorbs any
        # overlap).
        with self._lock:
            prev_uptime = (state.health or {}).get("uptimeSec")
        new_uptime = (health or {}).get("uptimeSec")
        if (
            isinstance(prev_uptime, (int, float))
            and isinstance(new_uptime, (int, float))
            and new_uptime < prev_uptime
            and state.span_cursor
        ):
            logger.info(
                "collector: %s restarted (uptime %.1fs -> %.1fs); span "
                "cursor reset", state.url, prev_uptime, new_uptime,
            )
            with self._lock:
                state.span_cursor = 0
        spans, hwm = self._pull_spans(state)
        with self._lock:
            state.ring.append((now, samples))
            state.families = families
            state.up = True
            state.ready = ready
            state.health = health
            state.last_error = None
            state.last_scrape_s = now
            if hwm is not None:
                state.span_cursor = hwm
            for span in spans:
                key = (
                    state.instance,
                    span.get("traceId"),
                    span.get("spanId"),
                )
                if key in self._span_keys:
                    continue
                self._span_keys.add(key)
                entry = dict(span)
                entry["instance"] = state.instance
                self._spans.append(entry)
            while len(self._spans) > self.span_retention:
                evicted = self._spans.popleft()
                self._span_keys.discard((
                    evicted.get("instance"),
                    evicted.get("traceId"),
                    evicted.get("spanId"),
                ))
        if spans:
            self._m_spans.labels(target=state.instance).inc(len(spans))
        self._m_scrapes.labels(target=state.instance, outcome="ok").inc()
        self._m_scrape_seconds.observe(time.perf_counter() - t0)

    def _pull_spans(
        self, state: _TargetState
    ) -> "Tuple[List[dict], Optional[int]]":
        """Incremental span pull: only spans past the target's cursor
        come over the wire. A target whose dump is auth-gated (and no
        key/secret was configured) or that predates the cursor protocol
        simply contributes no spans — metrics federation is unaffected."""
        params: Dict[str, str] = {"since": str(state.span_cursor)}
        if self.access_key:
            params["accessKey"] = self.access_key
        if self.secret:
            params["secret"] = self.secret
        def fetch(since: str):
            q = dict(params)
            q["since"] = since
            url = (
                state.url
                + "/debug/traces.json?"
                + urllib.parse.urlencode(q)
            )
            return json.loads(self._fetch(url).decode("utf-8"))

        try:
            payload = fetch(str(state.span_cursor))
            seq = payload.get("seq")
            if isinstance(seq, int) and seq < state.span_cursor:
                # the process restarted (its span sequence reset under
                # our cursor): re-pull the whole ring NOW — waiting for
                # the next poll would drop every span below the stale
                # cursor (the dedup key set absorbs any overlap)
                logger.info(
                    "collector: %s span sequence reset (%d -> %d); "
                    "re-pulling from scratch", state.url,
                    state.span_cursor, seq,
                )
                payload = fetch("0")
                seq = payload.get("seq")
        except Exception:
            logger.debug(
                "collector: span pull from %s failed", state.url,
                exc_info=True,
            )
            return [], None
        spans = payload.get("spans") or []
        if not isinstance(seq, int):
            # a pre-cursor server answered with a full dump and no
            # high-water mark: take the spans, keep the cursor at 0
            # (the dedup key set absorbs the re-downloads)
            return list(spans), None
        return list(spans), seq

    def poll_once(self) -> dict:
        """One scrape sweep over every registered target — CONCURRENT,
        so a few dead targets eating connect timeouts cannot stall
        scrape freshness (and the SLO windows' snapshot spacing) for
        the healthy ones — then an SLO evaluation pass. Returns a
        summary (the CLI logs it)."""
        states = self._states()
        if len(states) > 1:
            with concurrent.futures.ThreadPoolExecutor(
                max_workers=min(8, len(states)),
                thread_name_prefix="collector-scrape",
            ) as pool:
                list(pool.map(self._poll_target, states))
        elif states:
            self._poll_target(states[0])
        report = self.evaluate_slos()
        self.evaluate_ledger()
        experiments = self.evaluate_experiments()
        summary = {
            "targets": len(states),
            "up": sum(1 for s in states if s.up),
            "alerts": sum(1 for r in report if r["firing"]),
        }
        if experiments:
            summary["experiments"] = {
                r["experiment"]: r["status"] for r in experiments
            }
        return summary

    def evaluate_ledger(self) -> dict:
        """The device-ledger fleet view: total registered residency and
        the worst per-target drift across the latest scrapes; sets the
        ``pio_fleet_ledger_bytes`` / ``pio_ledger_drift_alert`` gauges
        and returns the fleet.json ``ledger`` block."""
        total = 0.0
        worst_drift = None
        for state in self._states():
            with self._lock:
                latest = state.latest()
            if latest is None:
                continue
            samples = latest[1]
            total += _metrics.counter_sum(
                samples, "pio_device_ledger_bytes"
            )
            for key, value in samples.items():
                if (
                    _metrics.sample_family_name(key)
                    == "pio_device_ledger_drift_bytes"
                ):
                    if worst_drift is None or abs(value) > abs(worst_drift):
                        worst_drift = value
        alert = (
            worst_drift is not None
            and abs(worst_drift) > DRIFT_ALERT_BYTES
        )
        self._m_fleet_ledger.set(total)
        self._m_drift_alert.set(1.0 if alert else 0.0)
        out: dict = {
            "hbm_mb": round(total / 2**20, 3),
            "drift_alert": bool(alert),
            "drift_threshold_mb": round(DRIFT_ALERT_BYTES / 2**20, 1),
        }
        if worst_drift is not None:
            out["max_drift_mb"] = round(worst_drift / 2**20, 3)
        return out

    def capture_profile(self, target_url: str, seconds: float = 2.0) -> dict:
        """Trigger one bounded profiler capture on a fleet target
        (``POST /debug/profile`` with the collector's configured
        accessKey/secret forwarded) and return its payload — the zipped
        trace archive base64-encoded plus its file listing."""
        params: Dict[str, str] = {"seconds": str(float(seconds))}
        if self.access_key:
            params["accessKey"] = self.access_key
        if self.secret:
            params["secret"] = self.secret
        url = (
            target_url.rstrip("/")
            + "/debug/profile?"
            + urllib.parse.urlencode(params)
        )
        req = urllib.request.Request(url, data=b"", method="POST")
        with urllib.request.urlopen(
            req, timeout=float(seconds) + 30.0
        ) as resp:
            return json.loads(resp.read().decode("utf-8"))

    def run(self, stop_event: Optional[threading.Event] = None) -> None:
        """The poll loop (stop-event idiom; ``pio collector`` wires
        SIGINT/SIGTERM to the event)."""
        stop = stop_event if stop_event is not None else self._stop
        hb = _health.heartbeat(
            "telemetry-collector", deadline_s=COLLECTOR_DEADLINE_S
        )
        while not stop.is_set():
            with hb.busy():
                try:
                    self.poll_once()
                except Exception:
                    logger.exception("collector poll sweep failed")
            if stop.wait(self.poll_interval_s):
                break

    def start(self) -> "Collector":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self.run, name="telemetry-collector", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def last_poll_age_s(self) -> Optional[float]:
        """Seconds since the newest successful target scrape (the
        collector server's readiness probe)."""
        with self._lock:
            newest = max(
                (
                    s.last_scrape_s
                    for s in self._targets.values()
                    if s.last_scrape_s is not None
                ),
                default=None,
            )
        return None if newest is None else max(0.0, time.time() - newest)

    # -- federation --

    def federated_families(self) -> Dict[str, dict]:
        """Merge every target's NEWEST typed snapshot exactly:

        - counters and histogram samples (cumulative ``_bucket`` /
          ``_sum`` / ``_count``) SUM across targets per identical label
          set — fixed bucket bounds are what make the histogram sum a
          true union (PR 6's invariant, now applied fleet-wide);
        - gauges (and untyped samples) gain an ``instance`` label and
          are NEVER summed — three workers' RSS gauges stay three
          samples.

        Returns ``{family: {"kind", "help", "rows": {(sample_name,
        labels): value}}}``; render with :meth:`render_federated`.
        """
        merged: Dict[str, dict] = {}
        for state in self._states():
            with self._lock:
                families = state.families
                instance = state.instance
            if not families:
                continue
            for name, fam in families.items():
                out = merged.setdefault(
                    name,
                    {"kind": fam["kind"], "help": fam["help"], "rows": {}},
                )
                if out["kind"] == "untyped" and fam["kind"] != "untyped":
                    out["kind"] = fam["kind"]
                if not out["help"]:
                    out["help"] = fam["help"]
                summing = fam["kind"] in ("counter", "histogram")
                for sample_name, labels, value in fam["samples"]:
                    if summing:
                        key = (sample_name, labels)
                        out["rows"][key] = out["rows"].get(key, 0.0) + value
                    else:
                        key = (
                            sample_name,
                            labels + (("instance", instance),),
                        )
                        out["rows"][key] = value
        return merged

    def render_federated(self) -> str:
        """The fleet-level ``GET /metrics`` body: the federated families
        as Prometheus text 0.0.4. Samples render deterministically
        (histogram buckets in ascending ``le`` order inside each label
        set; everything else sorted by label values), so two renders of
        the same snapshots are byte-identical."""
        lines: List[str] = []
        merged = self.federated_families()
        for name in sorted(merged):
            fam = merged[name]
            if fam["help"]:
                lines.append(f"# HELP {name} {fam['help']}")
            lines.append(f"# TYPE {name} {fam['kind']}")
            lines.extend(self._render_rows(fam["rows"]))
        return "\n".join(lines) + "\n"

    @staticmethod
    def _render_rows(rows: Dict[tuple, float]) -> List[str]:
        def le_rank(labels: tuple) -> tuple:
            # order histogram bucket lines by ascending bound, +Inf last
            le = dict(labels).get("le")
            if le is None:
                return (0, 0.0)
            return (1, math.inf if le == "+Inf" else float(le))

        def sort_key(item):
            (sample_name, labels), _ = item
            others = tuple(
                (k, v) for k, v in labels if k != "le"
            )
            return (sample_name, others, le_rank(labels))

        out = []
        for (sample_name, labels), value in sorted(
            rows.items(), key=sort_key
        ):
            label_str = ""
            if labels:
                pairs = ",".join(
                    f'{k}="{_metrics._escape_label_value(v)}"'
                    for k, v in labels
                )
                label_str = "{" + pairs + "}"
            out.append(f"{sample_name}{label_str} {_metrics._fmt(value)}")
        return out

    # -- the fleet view (/api/fleet.json) --

    _WORK_COUNTERS = (
        "pio_serving_requests_total",
        "pio_events_ingested_total",
        "pio_gateway_rpc_total",
    )

    def _windowed(
        self, state: _TargetState, window_s: float
    ) -> Optional[Tuple[float, Dict[str, float]]]:
        """(actual window seconds, counter deltas) for one target, or
        None without at least two snapshots."""
        with self._lock:
            latest = state.latest()
            if latest is None:
                return None
            base = state.sample_at(latest[0] - window_s)
        if base is None or base[0] >= latest[0]:
            return None
        return latest[0] - base[0], _delta_samples(latest[1], base[1])

    def _target_row(self, state: _TargetState, window_s: float) -> dict:
        with self._lock:
            row: dict = {
                "url": state.url,
                "instance": state.instance,
                "up": state.up,
                "ready": state.ready,
            }
            if state.last_error:
                row["error"] = state.last_error
            latest = state.latest()
            health = state.health
        if health and "uptimeSec" in health:
            row["uptime_s"] = health["uptimeSec"]
        if latest is None:
            return row
        samples = latest[1]
        work = sum(
            _metrics.counter_sum(samples, c) for c in self._WORK_COUNTERS
        )
        row["requests"] = int(work)
        p50 = _metrics.histogram_quantile_from_samples(
            samples, "pio_serving_latency_seconds", 0.5
        )
        if p50 is not None:
            row["p50_ms"] = p50 * 1e3
            row["p99_ms"] = (
                _metrics.histogram_quantile_from_samples(
                    samples, "pio_serving_latency_seconds", 0.99
                )
                or 0.0
            ) * 1e3
        errors = _metrics.counter_sum(samples, "pio_http_errors_total")
        if errors:
            row["errors"] = int(errors)
        # device-plane columns: registered HBM residency (with a
        # per-component breakdown — the `pio top` detail view), the
        # ledger-vs-memory_stats drift, padding waste, and shard skew
        hbm = _metrics.counter_sum(samples, "pio_device_ledger_bytes")
        if hbm:
            row["hbm_mb"] = hbm / 2**20
            comps: Dict[str, float] = {}
            for key, value in samples.items():
                if (
                    _metrics.sample_family_name(key)
                    != "pio_device_ledger_bytes"
                    or not value
                ):
                    continue
                c = _metrics.sample_label_value(key, "component") or "?"
                comps[c] = comps.get(c, 0.0) + value
            row["hbm_components_mb"] = {
                c: round(v / 2**20, 3) for c, v in sorted(comps.items())
            }
        drift = _metrics.gauge_max(
            samples, "pio_device_ledger_drift_bytes"
        )
        if drift:
            row["drift_mb"] = drift / 2**20
        pad = _metrics.gauge_max(samples, "pio_padding_waste_ratio")
        if pad is not None:
            row["pad"] = round(pad, 4)
        skew = _metrics.gauge_max(samples, "pio_retrieval_shard_skew")
        if skew is not None:
            row["skew"] = round(skew, 3)
        # quantized-residency detail (pio_retrieval_bytes_per_item):
        # the same "prec:bytesB" string the direct-scrape console shows
        from predictionio_tpu.tools.top import (
            _short_vid,
            attributed_hit_rates,
            experiment_info,
            quantized_residency,
        )

        prec = quantized_residency(samples)
        if prec is not None:
            row["prec"] = prec
        # model-quality columns, per version (an experiment's arms must
        # never blend into one number — `pio top --collector` renders
        # these straight off the federated row)
        hits = attributed_hit_rates(samples)
        if len(hits) == 1:
            row["hit_rate"] = round(next(iter(hits.values())) * 100.0, 1)
        elif hits:
            row["hit_rate"] = " ".join(
                f"{_short_vid(v)}:{r * 100.0:.1f}"
                for v, r in sorted(hits.items())
            )
        exp = experiment_info(samples)
        if exp is not None:
            row["exp"] = exp
        windowed = self._windowed(state, window_s)
        if windowed is not None:
            span_s, delta = windowed
            row["window_s"] = round(span_s, 3)
            window_work = sum(
                _metrics.counter_sum(delta, c) for c in self._WORK_COUNTERS
            )
            row["rate"] = window_work / span_s
            wp50 = _metrics.histogram_quantile_from_samples(
                delta, "pio_serving_latency_seconds", 0.5
            )
            if wp50 is not None:
                row["window_p50_ms"] = wp50 * 1e3
                row["window_p99_ms"] = (
                    _metrics.histogram_quantile_from_samples(
                        delta, "pio_serving_latency_seconds", 0.99
                    )
                    or 0.0
                ) * 1e3
        return row

    def fleet_json(self, window_s: float = 60.0) -> dict:
        """The ``/api/fleet.json`` payload: one row per target (rates
        and windowed p50/p99 computed from snapshot DELTAS over
        ``window_s``), a fleet-level aggregate over the union of the
        latest snapshots, and the current SLO report."""
        states = self._states()
        rows = [self._target_row(s, window_s) for s in states]
        fleet: dict = {
            "targets": len(rows),
            "up": sum(1 for r in rows if r.get("up")),
            "rate": sum(r.get("rate", 0.0) for r in rows),
            "requests": sum(r.get("requests", 0) for r in rows),
        }
        union: Dict[str, float] = {}
        union_window: Dict[str, float] = {}
        for state in states:
            with self._lock:
                latest = state.latest()
            if latest is None:
                continue
            for key, value in latest[1].items():
                union[key] = union.get(key, 0.0) + value
            windowed = self._windowed(state, window_s)
            if windowed is None:
                continue
            for key, value in windowed[1].items():
                union_window[key] = union_window.get(key, 0.0) + value
        p50 = _metrics.histogram_quantile_from_samples(
            union, "pio_serving_latency_seconds", 0.5
        )
        if p50 is not None:
            fleet["p50_ms"] = p50 * 1e3
            fleet["p99_ms"] = (
                _metrics.histogram_quantile_from_samples(
                    union, "pio_serving_latency_seconds", 0.99
                )
                or 0.0
            ) * 1e3
        wp99 = _metrics.histogram_quantile_from_samples(
            union_window, "pio_serving_latency_seconds", 0.99
        )
        if wp99 is not None:
            fleet["window_p99_ms"] = wp99 * 1e3
        return {
            "ts": time.time(),
            "window_s": window_s,
            "targets": rows,
            "fleet": fleet,
            "ledger": self.evaluate_ledger(),
            "slos": self.slo_report(),
            "alerts": self.alerts(),
            "experiments": self.experiment_reports(),
        }

    # -- trace stitching (/api/traces.json) --

    def stitched_spans(
        self,
        trace_id: Optional[str] = None,
        limit: int = DEFAULT_SPAN_RETENTION,
    ) -> List[dict]:
        """The fleet's spans joined across targets (each annotated with
        the ``instance`` it was pulled from), sorted by start time so
        ``tracing.format_trace`` renders one coherent tree per trace."""
        with self._lock:
            spans = list(self._spans)
        if trace_id:
            spans = [s for s in spans if s.get("traceId") == trace_id]
        spans.sort(key=lambda s: s.get("startMs", 0.0))
        return spans[-limit:]

    def traces_json(
        self, trace_id: Optional[str] = None, limit: int = 4096
    ) -> dict:
        spans = self.stitched_spans(trace_id, limit)
        processes = sorted({s["instance"] for s in spans})
        return {
            "spans": spans,
            "traces": len({s.get("traceId") for s in spans}),
            "instances": processes,
        }

    # -- the SLO burn-rate engine --

    def _fleet_window_delta(self, window_s: float) -> Tuple[float, Dict]:
        """Union of per-target counter deltas over ``window_s`` (each
        target diffed against ITS OWN ring, so scrape-phase offsets
        between targets never manufacture deltas). Returns the widest
        actual window span seen."""
        union: Dict[str, float] = {}
        actual = 0.0
        for state in self._states():
            windowed = self._windowed(state, window_s)
            if windowed is None:
                continue
            span_s, delta = windowed
            actual = max(actual, span_s)
            for key, value in delta.items():
                union[key] = union.get(key, 0.0) + value
        return actual, union

    @staticmethod
    def _errors_5xx(delta: Dict[str, float], server_substr: str) -> float:
        total = 0.0
        for key, value in delta.items():
            if _metrics.sample_family_name(key) != "pio_http_errors_total":
                continue
            status = _metrics.sample_label_value(key, "status") or ""
            server = _metrics.sample_label_value(key, "server") or ""
            if status.startswith("5") and server_substr in server:
                total += value
        return total

    @staticmethod
    def _latency_bad_fraction(
        delta: Dict[str, float], threshold_s: float
    ) -> Optional[float]:
        by_le: Dict[float, float] = {}
        for key, value in delta.items():
            if (
                _metrics.sample_family_name(key)
                != "pio_serving_latency_seconds_bucket"
            ):
                continue
            le = _metrics.sample_label_value(key, "le")
            if le is None:
                continue
            bound = math.inf if le == "+Inf" else float(le)
            by_le[bound] = by_le.get(bound, 0.0) + value
        if not by_le:
            return None
        total = by_le.get(math.inf, max(by_le.values()))
        if total <= 0:
            return None
        # clamp the threshold UP to the nearest fixed bucket bound: the
        # cumulative count there is exact (never interpolated). A
        # threshold PAST the largest finite bound clamps DOWN to it —
        # "good" must not collapse to zero and page on all traffic just
        # because the declared threshold overshot the ladder.
        finite = sorted(b for b in by_le if b != math.inf)
        if not finite:
            return None
        good_bound = next(
            (b for b in finite if b >= threshold_s), finite[-1]
        )
        good = by_le[good_bound]
        return max(0.0, (total - good) / total)

    def _bad_fraction(
        self, slo: SLODef, delta: Dict[str, float]
    ) -> Optional[float]:
        if slo.kind == "availability":
            good = _metrics.counter_sum(delta, "pio_serving_requests_total")
            bad = self._errors_5xx(delta, "Engine")
            denom = good + bad
            return (bad / denom) if denom > 0 else None
        if slo.kind == "latency":
            return self._latency_bad_fraction(
                delta, slo.latency_threshold_s
            )
        if slo.kind == "ingest_error_rate":
            good = _metrics.counter_sum(delta, "pio_events_ingested_total")
            bad = self._errors_5xx(delta, "Event")
            denom = good + bad
            return (bad / denom) if denom > 0 else None
        return None

    def evaluate_slos(self) -> List[dict]:
        """Evaluate every SLO over its fast and slow windows, set the
        ``pio_slo_burn_rate{slo,window}`` / ``pio_slo_alert{slo}``
        gauges, and cache the report for ``/api/alerts.json``. Windows
        without enough retention (or without any matching traffic)
        report burn 0 and never fire — an empty fleet is not an outage."""
        report: List[dict] = []
        deltas: Dict[float, Tuple[float, Dict]] = {}
        for slo in self.slos:
            windows: Dict[str, dict] = {}
            firing = True
            for label, window_s in (
                ("fast", slo.fast_window_s),
                ("slow", slo.slow_window_s),
            ):
                if window_s not in deltas:
                    deltas[window_s] = self._fleet_window_delta(window_s)
                actual_s, delta = deltas[window_s]
                frac = self._bad_fraction(slo, delta)
                budget = 1.0 - slo.objective
                burn = (frac / budget) if frac is not None else 0.0
                windows[label] = {
                    "window_s": window_s,
                    "actual_window_s": round(actual_s, 3),
                    "bad_fraction": frac,
                    "burn_rate": round(burn, 6),
                }
                self._m_burn.labels(slo=slo.name, window=label).set(burn)
                if frac is None or burn < slo.burn_threshold:
                    firing = False
            self._m_alert.labels(slo=slo.name).set(1.0 if firing else 0.0)
            report.append(
                {
                    "slo": slo.name,
                    "kind": slo.kind,
                    "objective": slo.objective,
                    "burn_threshold": slo.burn_threshold,
                    "windows": windows,
                    "firing": firing,
                }
            )
        with self._lock:
            self._last_slo_report = report
            self._last_alerts = [r for r in report if r["firing"]]
        return report

    def slo_report(self) -> List[dict]:
        with self._lock:
            return list(self._last_slo_report)

    def alerts(self) -> List[dict]:
        with self._lock:
            return list(self._last_alerts)

    def alerts_json(self) -> dict:
        return {
            "ts": time.time(),
            "slos": self.slo_report(),
            "alerts": self.alerts(),
        }

    # -- the sequential experimentation engine --

    # windowed per-variant p99 for the latency guardrail reads this
    # window's deltas (cumulative counts would let ancient traffic mask
    # a current regression)
    EXPERIMENT_LATENCY_WINDOW_S = 60.0

    def register_experiment(self, spec) -> bool:
        """Register an :class:`ExperimentSpec` for sequential
        evaluation. The per-variant attributed counts are read as deltas
        against the fleet's cumulative counters AT REGISTRATION, per
        target (a restarted worker clamps to zero). Re-registering an
        identical spec is a no-op (fleet-converge nudges are free);
        a different spec under the same name re-baselines."""
        with self._lock:
            existing = self._experiments.get(spec.name)
            if existing is not None and existing.spec == spec:
                return False
            baselines: Dict[str, Dict[str, float]] = {}
            for state in self._targets.values():
                latest = state.latest()
                if latest is not None:
                    baselines[state.url] = dict(latest[1])
            self._experiments[spec.name] = _ExperimentEntry(
                spec=spec,
                registered_s=time.time(),
                baselines=baselines,
            )
            self._last_experiments.pop(spec.name, None)
        return True

    def remove_experiment(self, name: str) -> bool:
        with self._lock:
            removed = self._experiments.pop(name, None)
            self._last_experiments.pop(name, None)
        if removed is not None:
            self._m_exp_decided.labels(experiment=name).set(0.0)
        return removed is not None

    def experiment_report(self, name: str) -> Optional[dict]:
        with self._lock:
            return self._last_experiments.get(name)

    def experiment_reports(self) -> List[dict]:
        with self._lock:
            return list(self._last_experiments.values())

    def experiments_json(self) -> dict:
        with self._lock:
            entries = [
                {
                    "spec": e.spec.to_json(),
                    "registered_s": e.registered_s,
                    "report": self._last_experiments.get(name),
                }
                for name, e in self._experiments.items()
            ]
        return {"ts": time.time(), "experiments": entries}

    def evaluate_experiments(self) -> List[dict]:
        """One peek of every registered experiment's sequential test
        over the federated ring — run on each poll tick exactly the way
        SLO burn rates are (the mSPRT is always-valid, so continuous
        peeking spends no extra alpha). Per-variant attributed counts
        come from ``pio_online_attributed_total{version=<variant>}``
        deltas since registration; the latency guardrail reads each
        arm's windowed ``pio_serving_latency_seconds`` p99. A verdict is
        STICKY: once crossed, later polls re-report it unchanged."""
        from predictionio_tpu.workflow.experiment import evaluate_sequential

        with self._lock:
            entries = list(self._experiments.items())
        if not entries:
            return []
        reports: List[dict] = []
        window_s = self.EXPERIMENT_LATENCY_WINDOW_S
        _, wdelta = self._fleet_window_delta(window_s)
        states = self._states()
        for name, entry in entries:
            if entry.final is not None:
                reports.append(entry.final)
                continue
            spec = entry.spec
            stats: Dict[str, Dict[str, object]] = {
                vid: {
                    "converted": 0.0,
                    "miss": 0.0,
                    "requests": 0.0,
                    "p99_s": None,
                }
                for vid in spec.variants
            }
            for state in states:
                with self._lock:
                    latest = state.latest()
                if latest is None:
                    continue
                base = entry.baselines.get(state.url, {})
                for key, value in latest[1].items():
                    family = _metrics.sample_family_name(key)
                    if family == "pio_online_attributed_total":
                        vid = _metrics.sample_label_value(key, "version")
                        outcome = _metrics.sample_label_value(
                            key, "outcome"
                        )
                        if vid in stats and outcome in (
                            "converted", "miss",
                        ):
                            stats[vid][outcome] += max(
                                0.0, value - base.get(key, 0.0)
                            )
                    elif family == "pio_serving_requests_total":
                        vid = _metrics.sample_label_value(key, "version")
                        if vid in stats:
                            stats[vid]["requests"] += max(
                                0.0, value - base.get(key, 0.0)
                            )
            per_variant_lat: Dict[str, Dict[str, float]] = {}
            for key, value in wdelta.items():
                if (
                    _metrics.sample_family_name(key)
                    == "pio_serving_latency_seconds_bucket"
                ):
                    vid = _metrics.sample_label_value(key, "version")
                    if vid in stats:
                        per_variant_lat.setdefault(vid, {})[key] = value
            for vid, sub in per_variant_lat.items():
                stats[vid]["p99_s"] = (
                    _metrics.histogram_quantile_from_samples(
                        sub, "pio_serving_latency_seconds", 0.99
                    )
                )
            report = evaluate_sequential(
                spec,
                stats,
                elapsed_s=time.time() - entry.registered_s,
            )
            for vid, v in report["variants"].items():
                self._m_exp_lambda.labels(
                    experiment=name, variant=vid
                ).set(v["log_lambda"])
                self._m_exp_rate.labels(
                    experiment=name, variant=vid
                ).set(v["hit_rate"] or 0.0)
                if v.get("p99_s") is not None:
                    self._m_exp_p99.labels(
                        experiment=name, variant=vid
                    ).set(v["p99_s"])
            decided = report["status"] != "running"
            self._m_exp_decided.labels(experiment=name).set(
                1.0 if decided else 0.0
            )
            if decided:
                entry.final = report
            reports.append(report)
        with self._lock:
            self._last_experiments = {
                r["experiment"]: r for r in reports
            }
        return reports
