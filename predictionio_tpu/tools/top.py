"""``pio top``: a live operator console over /metrics + /healthz + /readyz.

One screen per refresh for a FLEET of servers (event servers, engine
servers, storage gateways — any mix of URLs): liveness, readiness,
request/ingest rates (counter deltas between scrapes), serving latency
quantiles reconstructed from the exposition's cumulative histogram
buckets (the same ``quantile_from_buckets`` estimator status.json
uses), event-loop lag, HTTP error and continuous-training round
counters. Everything is derived from the three public endpoints — the
console holds no privileged access and works against any worker of an
SO_REUSEPORT fleet.

The refresh loop is shutdown-aware (stop-event idiom, the while-True
lint's sanctioned shape) and degrades per-server: an unreachable URL
renders as ``down`` instead of killing the console.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from predictionio_tpu.utils.metrics import (
    counter_sum,
    gauge_max,
    histogram_quantile_from_samples as histogram_quantile,
    parse_exposition,
    sample_family_name as _family_name,
    sample_label_value as _label_value,
)


def active_model_version(samples: Dict[str, float]) -> Optional[str]:
    """The version label(s) of ``pio_model_info`` samples at 1 — what
    this server is actively serving (a swap flips the old one to 0)."""
    active = [
        _label_value(key, "version")
        for key, value in samples.items()
        if _family_name(key) == "pio_model_info" and value == 1.0
    ]
    active = sorted(v for v in active if v)
    return ",".join(active) if active else None


def attributed_hit_rates(
    samples: Dict[str, float],
) -> Dict[str, float]:
    """PER-VERSION converted / (converted + miss) over the online
    feedback-join counters ('unknown' outcomes — expired or foreign
    prIds — are excluded from the denominator). Per-version is the only
    honest view: summing across versions blends a live experiment's
    arms into one meaningless number."""
    per: Dict[str, List[float]] = {}
    for key, value in samples.items():
        if _family_name(key) != "pio_online_attributed_total":
            continue
        version = _label_value(key, "version") or "?"
        outcome = _label_value(key, "outcome")
        if outcome == "converted":
            per.setdefault(version, [0.0, 0.0])[0] += value
        elif outcome == "miss":
            per.setdefault(version, [0.0, 0.0])[1] += value
    return {
        v: c / (c + m)
        for v, (c, m) in per.items()
        if (c + m) > 0
    }


def attributed_hit_rate(
    samples: Dict[str, float], version: Optional[str] = None
) -> Optional[float]:
    """One version's attributed hit rate; without ``version``, the sum
    across versions — only meaningful when a single version is serving
    (use :func:`attributed_hit_rates` otherwise)."""
    converted = missed = 0.0
    for key, value in samples.items():
        if _family_name(key) != "pio_online_attributed_total":
            continue
        if (
            version is not None
            and _label_value(key, "version") != version
        ):
            continue
        outcome = _label_value(key, "outcome")
        if outcome == "converted":
            converted += value
        elif outcome == "miss":
            missed += value
    denom = converted + missed
    return (converted / denom) if denom else None


def _short_vid(vid: str, limit: int = 8) -> str:
    return vid if len(vid) <= limit else vid[: limit - 1] + "…"


def experiment_info(samples: Dict[str, float]) -> Optional[str]:
    """EXP column detail from ``pio_experiment_info{experiment,variant}``
    (value = split fraction while running, 0 after): ``"exp-a
    v1:50/v2:50"``. None when no experiment is running on the server."""
    name = None
    per: Dict[str, float] = {}
    for key, value in samples.items():
        if _family_name(key) != "pio_experiment_info" or value <= 0:
            continue
        name = _label_value(key, "experiment") or "?"
        vid = _label_value(key, "variant") or "?"
        per[vid] = value
    if not per:
        return None
    detail = "/".join(
        f"{_short_vid(v)}:{round(s * 100):.0f}"
        for v, s in sorted(per.items())
    )
    return f"{name} {detail}"


def quantized_residency(samples: Dict[str, float]) -> Optional[str]:
    """PREC detail from ``pio_retrieval_bytes_per_item{precision}``:
    ``"int8:73B"`` — the residency precision(s) the server's retrieval
    tier is actually serving at and what each resident row costs. A
    float32-only (or retriever-less) server shows no detail."""
    per: Dict[str, float] = {}
    for key, value in samples.items():
        if _family_name(key) != "pio_retrieval_bytes_per_item":
            continue
        prec = _label_value(key, "precision")
        if prec and value > 0:
            per[prec] = max(per.get(prec, 0.0), value)
    if not per:
        return None
    return ",".join(f"{p}:{b:.0f}B" for p, b in sorted(per.items()))


def fetch_server(base_url: str, timeout: float = 5.0) -> dict:
    """One snapshot of a server's health + readiness + metrics. Network
    failures degrade to ``{"up": False}`` — the console must keep
    rendering a fleet with a dead member."""
    base = base_url.rstrip("/")
    out: dict = {"url": base_url, "up": False, "ready": None}
    try:
        with urllib.request.urlopen(base + "/healthz", timeout=timeout) as r:
            out["up"] = r.status == 200
            out["health"] = json.loads(r.read().decode("utf-8"))
    except Exception as e:
        out["error"] = str(e)
        return out
    try:
        req = urllib.request.Request(base + "/readyz")
        with urllib.request.urlopen(req, timeout=timeout) as r:
            out["ready"] = r.status == 200
            out["ready_detail"] = json.loads(r.read().decode("utf-8"))
    except urllib.error.HTTPError as e:  # 503 carries the detail payload
        out["ready"] = False
        try:
            out["ready_detail"] = json.loads(e.read().decode("utf-8"))
        except Exception:
            pass
    except Exception:
        pass
    try:
        with urllib.request.urlopen(base + "/metrics", timeout=timeout) as r:
            out["metrics"] = parse_exposition(r.read().decode("utf-8"))
    except Exception:
        out["metrics"] = {}
    return out


_WORK_COUNTERS = (
    # "work done" counters per server kind; the rate column sums them
    "pio_serving_requests_total",
    "pio_events_ingested_total",
    "pio_gateway_rpc_total",
)


def _row(snap: dict, prev: Optional[dict], elapsed_s: float) -> dict:
    if not snap.get("up"):
        return {"url": snap["url"], "live": "DOWN", "ready": "-"}
    m = snap.get("metrics", {})
    row: dict = {
        "url": snap["url"],
        "live": "ok",
        "ready": (
            "ok" if snap.get("ready")
            else ("503" if snap.get("ready") is False else "-")
        ),
        "uptime_s": snap.get("health", {}).get("uptimeSec"),
    }
    work = sum(counter_sum(m, c) for c in _WORK_COUNTERS)
    if prev is not None and prev.get("up") and elapsed_s > 0:
        pm = prev.get("metrics", {})
        prev_work = sum(counter_sum(pm, c) for c in _WORK_COUNTERS)
        row["rate"] = max(0.0, (work - prev_work) / elapsed_s)
    row["requests"] = int(work)
    p50 = histogram_quantile(m, "pio_serving_latency_seconds", 0.5)
    p99 = histogram_quantile(m, "pio_serving_latency_seconds", 0.99)
    if p50 is not None:
        row["p50_ms"], row["p99_ms"] = p50 * 1e3, (p99 or 0.0) * 1e3
    lag = gauge_max(m, "pio_eventloop_lag_seconds")
    if lag is not None:
        row["lag_ms"] = lag * 1e3
    errors = counter_sum(m, "pio_http_errors_total")
    if errors:
        row["errors"] = int(errors)
    rounds = counter_sum(m, "pio_continuous_rounds_total")
    if rounds:
        row["rounds"] = int(rounds)
    delta = gauge_max(m, "pio_train_last_factor_delta")
    if delta is not None:
        row["last_delta"] = delta
    # retrieval tier: device-resident factor bytes (summed across the
    # server's retriever components) and the staleness of the resident
    # candidacy mask — an engine server whose mask age grows past the
    # constraint TTL has a wedged out-of-band refresh
    resident = counter_sum(m, "pio_retrieval_resident_bytes")
    if resident:
        row["resident_mb"] = resident / 2**20
    prec = quantized_residency(m)
    if prec is not None:
        row["prec"] = prec
    mask_age = gauge_max(m, "pio_retrieval_mask_age_seconds")
    if mask_age is not None:
        row["mask_age_s"] = mask_age
    # device-plane columns (utils/device_ledger.py + the efficiency
    # gauges): total registered HBM residency, ledger-vs-memory_stats
    # drift, worst padding-waste site, and cross-shard retrieval skew
    hbm = counter_sum(m, "pio_device_ledger_bytes")
    if hbm:
        row["hbm_mb"] = hbm / 2**20
    drift = gauge_max(m, "pio_device_ledger_drift_bytes")
    if drift:
        row["drift_mb"] = drift / 2**20
    pad = gauge_max(m, "pio_padding_waste_ratio")
    if pad is not None:
        row["pad"] = round(pad, 3)
    skew = gauge_max(m, "pio_retrieval_shard_skew")
    if skew is not None:
        row["skew"] = round(skew, 2)
    # model-quality columns: the actively served version(s) and the
    # online attributed hit rate (converted / attributed, across the
    # fleet's feedback join) — an engine server shows VERSION, an event
    # server (the ingest side of the join) shows HIT%
    version = active_model_version(m)
    if version is not None:
        row["version"] = (
            version if len(version) <= 12 else version[:11] + "…"
        )
    # HIT% is per version: one serving version renders the bare number
    # (the pre-experiment shape); several — a live experiment's arms, or
    # versions around a swap — render "vid:rate" pairs, never blended
    hits = attributed_hit_rates(m)
    if len(hits) == 1:
        row["hit_rate"] = round(next(iter(hits.values())) * 100.0, 1)
    elif hits:
        row["hit_rate"] = " ".join(
            f"{_short_vid(v)}:{r * 100.0:.1f}"
            for v, r in sorted(hits.items())
        )
    exp = experiment_info(m)
    if exp is not None:
        row["exp"] = exp
    # storage-cluster column (data/storage/cluster.py): per-node breaker
    # gauges from the process embedding the routing client — "2/3"
    # means one node's breaker is open; "+1s" appends the count of
    # STALE nodes awaiting resync (docs/STORAGE.md)
    node_up = [
        v for k, v in m.items()
        if _family_name(k) == "pio_cluster_node_up"
    ]
    if node_up:
        stale = int(counter_sum(m, "pio_cluster_node_stale"))
        detail = ""
        if stale:
            # how long the worst replica has been out of the read path,
            # and its measured event-time lag to the resync source —
            # "+1s(34s/12s)" = 1 stale node, stale 34s, 12s behind
            age = gauge_max(m, "pio_cluster_stale_age_seconds") or 0.0
            lag = gauge_max(m, "pio_cluster_resync_lag_seconds") or 0.0
            detail = f"+{stale}s({age:.0f}s" + (
                f"/{lag:.0f}s)" if lag else ")"
            )
        row["nodes"] = f"{int(sum(node_up))}/{len(node_up)}" + detail
    # fleet-supervisor column (tools/fleet.py): crashed workers the
    # supervisor restarted — present when the scraped process runs a
    # supervised `pio deploy --workers` fleet
    restarts = counter_sum(m, "pio_fleet_worker_restarts_total")
    if restarts:
        row["restarts"] = int(restarts)
    stalled = snap.get("ready_detail", {}).get("stalledDaemons") or {}
    if stalled:
        row["stalled"] = ",".join(sorted(stalled))
    return row


_COLUMNS = (
    ("url", "SERVER", 28),
    ("live", "LIVE", 5),
    ("ready", "READY", 6),
    ("rate", "REQ/S", 8),
    ("requests", "TOTAL", 9),
    ("p50_ms", "P50ms", 8),
    ("p99_ms", "P99ms", 8),
    ("lag_ms", "LAGms", 7),
    ("errors", "ERR", 5),
    ("version", "VERSION", 12),
    ("hit_rate", "HIT%", 6),
    ("exp", "EXP", 16),
    ("rounds", "ROUNDS", 7),
    ("last_delta", "CONV", 9),
    ("resident_mb", "RES_MB", 7),
    ("prec", "PREC", 10),
    ("hbm_mb", "HBM_MB", 7),
    ("pad", "PAD", 6),
    ("skew", "SKEW", 5),
    ("mask_age_s", "MASKs", 6),
    ("nodes", "NODES", 7),
    ("restarts", "RESTART", 8),
    ("stalled", "STALLED", 20),
)


def _fmt(value) -> str:
    if value is None:
        return "-"
    if isinstance(value, float):
        return f"{value:.4g}"
    return str(value)


def render(rows: List[dict]) -> str:
    lines = [
        " ".join(title.ljust(width) for _, title, width in _COLUMNS)
    ]
    for row in rows:
        # pad to the column width but never truncate: a long stalled-
        # daemon list pushes its row wide rather than hiding daemons
        lines.append(
            " ".join(
                _fmt(row.get(key)).ljust(width)
                for key, _, width in _COLUMNS
            ).rstrip()
        )
    return "\n".join(lines)


def fetch_fleet(collector_url: str, timeout: float = 5.0) -> dict:
    """One /api/fleet.json snapshot from a telemetry collector
    (utils/telemetry.py); degrades to ``{"error": …}`` so the console
    keeps rendering when the collector is down."""
    url = collector_url.rstrip("/") + "/api/fleet.json"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read().decode("utf-8"))
    except Exception as e:
        return {"error": str(e), "targets": []}


def _row_from_fleet(t: dict) -> dict:
    """Map one fleet.json target entry onto the console's columns —
    rates and windowed quantiles come from the collector's retention
    ring, so the console needs no scrape-to-scrape diffing of its own."""
    if not t.get("up"):
        row = {"url": t.get("url", "?"), "live": "DOWN", "ready": "-"}
        return row
    row = {
        "url": t["url"],
        "live": "ok",
        "ready": (
            "ok" if t.get("ready")
            else ("503" if t.get("ready") is False else "-")
        ),
        "uptime_s": t.get("uptime_s"),
        "requests": t.get("requests"),
        "rate": t.get("rate"),
        "errors": t.get("errors"),
    }
    # prefer the windowed (over-time) quantiles; lifetime as fallback
    p50 = t.get("window_p50_ms", t.get("p50_ms"))
    if p50 is not None:
        row["p50_ms"] = p50
        row["p99_ms"] = t.get("window_p99_ms", t.get("p99_ms"))
    # device-plane + model-quality columns federated by the collector
    for key in ("hbm_mb", "pad", "skew", "drift_mb", "prec", "hit_rate", "exp"):
        if t.get(key) is not None:
            row[key] = t[key]
    return row


def render_fleet(fleet: dict) -> str:
    """A collector-fed frame: the per-target table plus an SLO footer
    (burn rates per window; firing alerts called out)."""
    lines = [render([_row_from_fleet(t) for t in fleet.get("targets", [])])]
    if fleet.get("error"):
        lines.append(f"collector unreachable: {fleet['error']}")
    f = fleet.get("fleet") or {}
    if f:
        parts = [f"fleet: {f.get('up', 0)}/{f.get('targets', 0)} up"]
        if f.get("rate") is not None:
            parts.append(f"{f['rate']:.1f} req/s")
        if f.get("window_p99_ms") is not None:
            parts.append(f"window p99 {f['window_p99_ms']:.2f}ms")
        elif f.get("p99_ms") is not None:
            parts.append(f"p99 {f['p99_ms']:.2f}ms")
        lines.append("  ".join(parts))
    ledger = fleet.get("ledger") or {}
    if ledger:
        line = f"ledger: {ledger.get('hbm_mb', 0.0):.3g} MB resident"
        if ledger.get("max_drift_mb") is not None:
            line += f"  max drift {ledger['max_drift_mb']:.3g} MB"
        if ledger.get("drift_alert"):
            line += "  DRIFT ALERT"
        lines.append(line)
    slos = fleet.get("slos") or []
    if slos:
        rendered = []
        for s in slos:
            w = s.get("windows", {})
            fast = (w.get("fast") or {}).get("burn_rate")
            slow = (w.get("slow") or {}).get("burn_rate")
            tag = " FIRING" if s.get("firing") else ""
            rendered.append(
                f"{s['slo']} burn fast={fast} slow={slow}{tag}"
            )
        lines.append("slo: " + "; ".join(rendered))
    experiments = fleet.get("experiments") or []
    if experiments:
        rendered = []
        for e in experiments:
            part = f"{e.get('experiment')} {e.get('status')}"
            if e.get("winner"):
                part += f" winner={_short_vid(str(e['winner']))}"
            rendered.append(part)
        lines.append("exp: " + "; ".join(rendered))
    return "\n".join(lines)


def run_top(
    urls: List[str],
    interval_s: float = 2.0,
    iterations: Optional[int] = None,
    stop_event: Optional[threading.Event] = None,
    out=None,
    clear: bool = True,
    collector: Optional[str] = None,
) -> int:
    """The console loop: scrape, diff against the previous scrape for
    rates, render. ``iterations=1`` is the scriptable one-shot
    (``pio top --once``); interactive runs clear the screen per frame
    and stop on the event (wired to SIGINT/SIGTERM by the CLI). With
    ``collector`` set, the whole fleet renders from that collector's
    /api/fleet.json instead of per-server scrapes."""
    import sys
    import time

    out = out if out is not None else sys.stdout
    stop = stop_event if stop_event is not None else threading.Event()
    prev: Dict[str, dict] = {}
    prev_t: Optional[float] = None
    n = 0
    while not stop.is_set():
        if collector:
            frame = render_fleet(fetch_fleet(collector))
        else:
            snaps = [fetch_server(u) for u in urls]
            # rates use the MEASURED time between scrape cycles, not the
            # nominal interval: slow scrapes (a DOWN member eating its
            # connect timeout) must not inflate every other server's
            # REQ/S
            now = time.monotonic()
            elapsed_s = (now - prev_t) if prev_t is not None else 0.0
            prev_t = now
            rows = [
                _row(s, prev.get(s["url"]), elapsed_s) for s in snaps
            ]
            frame = render(rows)
            prev = {s["url"]: s for s in snaps}
        if clear and iterations != 1:
            out.write("\x1b[2J\x1b[H")
        out.write(frame + "\n")
        out.flush()
        n += 1
        if iterations is not None and n >= iterations:
            break
        if stop.wait(interval_s):
            break
    return 0
