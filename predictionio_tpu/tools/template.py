"""Engine template gallery.

Capability parity with the reference template commands
(tools/src/main/scala/io/prediction/tools/console/Template.scala:226-429
— ``pio template list|get`` fetching from a GitHub gallery, unzipping and
personalizing). This runtime ships its model families in-package, so the
gallery is local: ``list`` enumerates the built-in engine templates and
``get`` scaffolds a ready-to-run engine project directory (engine.json
wired to the packaged EngineFactory, plus a README with the train/deploy
commands). A ``template.json`` with ``pio.version.min`` is emitted and
checked like the reference's verifyTemplateMinVersion (:417-429).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Dict, List

from predictionio_tpu import __version__


@dataclasses.dataclass(frozen=True)
class TemplateMetaData:
    """Reference TemplateMetaData (Template.scala:66)."""

    name: str
    repo: str  # packaged module path (the local "repo")
    description: str
    engine_factory: str
    variant: Dict


TEMPLATES: List[TemplateMetaData] = [
    TemplateMetaData(
        name="recommendation",
        repo="predictionio_tpu.models.recommendation",
        description="ALS collaborative filtering over rate/buy events "
        "(reference scala-parallel-recommendation)",
        engine_factory="predictionio_tpu.models.recommendation.RecommendationEngineFactory",
        variant={
            "datasource": {"params": {"app_name": "MyApp"}},
            "algorithms": [
                {
                    "name": "als",
                    "params": {
                        "rank": 10,
                        "num_iterations": 20,
                        "lambda_": 0.01,
                        "seed": 3,
                    },
                }
            ],
        },
    ),
    TemplateMetaData(
        name="similarproduct",
        repo="predictionio_tpu.models.similarproduct",
        description="similar items by cosine over implicit-ALS item factors "
        "(reference scala-parallel-similarproduct)",
        engine_factory="predictionio_tpu.models.similarproduct.SimilarProductEngineFactory",
        variant={
            "datasource": {"params": {"app_name": "MyApp"}},
            "algorithms": [
                {
                    "name": "als",
                    "params": {"rank": 10, "num_iterations": 20, "lambda_": 0.01},
                }
            ],
        },
    ),
    TemplateMetaData(
        name="classification",
        repo="predictionio_tpu.models.classification",
        description="NaiveBayes classification over $set user properties "
        "(reference scala-parallel-classification)",
        engine_factory="predictionio_tpu.models.classification.ClassificationEngineFactory",
        variant={
            "datasource": {"params": {"app_name": "MyApp"}},
            "algorithms": [{"name": "naive", "params": {"lambda_": 1.0}}],
        },
    ),
    TemplateMetaData(
        name="ecommercerecommendation",
        repo="predictionio_tpu.models.ecommerce",
        description="ALS + live business rules (seen/unavailable items) "
        "(reference scala-parallel-ecommercerecommendation)",
        engine_factory="predictionio_tpu.models.ecommerce.ECommerceEngineFactory",
        variant={
            "datasource": {"params": {"app_name": "MyApp"}},
            "algorithms": [
                {
                    "name": "ecomm",
                    "params": {
                        "app_name": "MyApp",
                        "unseen_only": True,
                        "seen_events": ["buy", "view"],
                        "rank": 10,
                        "num_iterations": 20,
                    },
                }
            ],
        },
    ),
]


def template_list() -> List[TemplateMetaData]:
    return list(TEMPLATES)


def template_get(name: str, directory: str, app_name: str = "MyApp") -> str:
    """Scaffold an engine project directory; returns the directory."""
    matches = [t for t in TEMPLATES if t.name == name]
    if not matches:
        raise KeyError(
            f"template {name!r} not found; available: "
            f"{[t.name for t in TEMPLATES]}"
        )
    t = matches[0]
    os.makedirs(directory, exist_ok=False)

    def personalize(v):
        if isinstance(v, dict):
            return {k: personalize(x) for k, x in v.items()}
        if isinstance(v, list):
            return [personalize(x) for x in v]
        return app_name if v == "MyApp" else v

    variant = personalize(t.variant)
    engine_json = {
        "id": name,
        "version": "0.1.0",
        "description": t.description,
        "engineFactory": t.engine_factory,
        **variant,
    }
    with open(os.path.join(directory, "engine.json"), "w") as f:
        json.dump(engine_json, f, indent=2)
        f.write("\n")
    with open(os.path.join(directory, "template.json"), "w") as f:
        json.dump({"pio": {"version": {"min": __version__}}}, f)
        f.write("\n")
    with open(os.path.join(directory, "README.md"), "w") as f:
        f.write(
            f"# {name} engine\n\n{t.description}\n\n"
            "```sh\n"
            f"pio app new {app_name}\n"
            "pio build\npio train\npio deploy\n"
            "```\n\n"
            f"Engine components: `{t.repo}.engine`. Customize by\n"
            "subclassing its DataSource/Preparator/Algorithm/Serving and\n"
            "pointing `engineFactory` at your own EngineFactory.\n"
        )
    return directory


def verify_template_min_version(directory: str) -> bool:
    """Reference verifyTemplateMinVersion (Template.scala:417-429)."""
    path = os.path.join(directory, "template.json")
    if not os.path.exists(path):
        return True
    with open(path) as f:
        meta = json.load(f)
    min_version = (
        meta.get("pio", {}).get("version", {}).get("min", "0")
    )

    def parse(v: str):
        out = []
        for part in v.split("."):
            m = re.match(r"\d+", part)  # leading digits only: "0rc1" -> 0
            out.append(int(m.group()) if m else 0)
        return out

    have, need = parse(__version__), parse(min_version)
    width = max(len(have), len(need))
    have += [0] * (width - len(have))
    need += [0] * (width - len(need))
    return have >= need
