"""Engine template gallery.

Capability parity with the reference template commands
(tools/src/main/scala/io/prediction/tools/console/Template.scala:226-429
— ``pio template list|get`` fetching from a GitHub gallery, unzipping and
personalizing). This runtime ships its model families in-package, so the
gallery is local: ``list`` enumerates the built-in engine templates and
``get`` scaffolds a ready-to-run engine project directory (engine.json
wired to the packaged EngineFactory, plus a README with the train/deploy
commands). A ``template.json`` with ``pio.version.min`` is emitted and
checked like the reference's verifyTemplateMinVersion (:417-429).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Dict, List

from predictionio_tpu import __version__
from predictionio_tpu.utils.version import version_gte

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class TemplateMetaData:
    """Reference TemplateMetaData (Template.scala:66)."""

    name: str
    repo: str  # packaged module path (the local "repo")
    description: str
    engine_factory: str
    variant: Dict


TEMPLATES: List[TemplateMetaData] = [
    TemplateMetaData(
        name="recommendation",
        repo="predictionio_tpu.models.recommendation",
        description="ALS collaborative filtering over rate/buy events "
        "(reference scala-parallel-recommendation)",
        engine_factory="predictionio_tpu.models.recommendation.RecommendationEngineFactory",
        variant={
            "datasource": {"params": {"app_name": "MyApp"}},
            "algorithms": [
                {
                    "name": "als",
                    "params": {
                        "rank": 10,
                        "num_iterations": 20,
                        "lambda_": 0.01,
                        "seed": 3,
                    },
                }
            ],
        },
    ),
    TemplateMetaData(
        name="similarproduct",
        repo="predictionio_tpu.models.similarproduct",
        description="similar items by cosine over implicit-ALS item factors "
        "(reference scala-parallel-similarproduct)",
        engine_factory="predictionio_tpu.models.similarproduct.SimilarProductEngineFactory",
        variant={
            "datasource": {"params": {"app_name": "MyApp"}},
            "algorithms": [
                {
                    "name": "als",
                    "params": {"rank": 10, "num_iterations": 20, "lambda_": 0.01},
                }
            ],
        },
    ),
    TemplateMetaData(
        name="classification",
        repo="predictionio_tpu.models.classification",
        description="NaiveBayes classification over $set user properties "
        "(reference scala-parallel-classification)",
        engine_factory="predictionio_tpu.models.classification.ClassificationEngineFactory",
        variant={
            "datasource": {"params": {"app_name": "MyApp"}},
            "algorithms": [{"name": "naive", "params": {"lambda_": 1.0}}],
        },
    ),
    TemplateMetaData(
        name="ecommercerecommendation",
        repo="predictionio_tpu.models.ecommerce",
        description="ALS + live business rules (seen/unavailable items) "
        "(reference scala-parallel-ecommercerecommendation)",
        engine_factory="predictionio_tpu.models.ecommerce.ECommerceEngineFactory",
        variant={
            "datasource": {"params": {"app_name": "MyApp"}},
            "algorithms": [
                {
                    "name": "ecomm",
                    "params": {
                        "app_name": "MyApp",
                        "unseen_only": True,
                        "seen_events": ["buy", "view"],
                        "rank": 10,
                        "num_iterations": 20,
                    },
                }
            ],
        },
    ),
]


def template_list() -> List[TemplateMetaData]:
    return list(TEMPLATES)


def template_get(name: str, directory: str, app_name: str = "MyApp") -> str:
    """Scaffold an engine project directory; returns the directory."""
    matches = [t for t in TEMPLATES if t.name == name]
    if not matches:
        raise KeyError(
            f"template {name!r} not found; available: "
            f"{[t.name for t in TEMPLATES]}"
        )
    t = matches[0]
    os.makedirs(directory, exist_ok=False)

    def personalize(v):
        if isinstance(v, dict):
            return {k: personalize(x) for k, x in v.items()}
        if isinstance(v, list):
            return [personalize(x) for x in v]
        return app_name if v == "MyApp" else v

    variant = personalize(t.variant)
    engine_json = {
        "id": name,
        "version": "0.1.0",
        "description": t.description,
        "engineFactory": t.engine_factory,
        **variant,
    }
    with open(os.path.join(directory, "engine.json"), "w") as f:
        json.dump(engine_json, f, indent=2)
        f.write("\n")
    with open(os.path.join(directory, "template.json"), "w") as f:
        json.dump({"pio": {"version": {"min": __version__}}}, f)
        f.write("\n")
    with open(os.path.join(directory, "README.md"), "w") as f:
        f.write(
            f"# {name} engine\n\n{t.description}\n\n"
            "```sh\n"
            f"pio app new {app_name}\n"
            "pio build\npio train\npio deploy\n"
            "```\n\n"
            f"Engine components: `{t.repo}.engine`. Customize by\n"
            "subclassing its DataSource/Preparator/Algorithm/Serving and\n"
            "pointing `engineFactory` at your own EngineFactory.\n"
        )
    return directory


GITHUB_API = "https://api.github.com"


def template_get_remote(
    repo: str,
    directory: str,
    app_name: str = "MyApp",
    ref: str = "",
    sha256: str = "",
    base_url: str = "",
    timeout: float = 30.0,
) -> str:
    """Fetch an engine template from a GitHub repository (``user/repo``)
    into ``directory`` — the reference's remote gallery path
    (console/Template.scala:226-415: tags API, archive download, unzip,
    personalize). Differences by design: stdlib urllib (proxy-aware via
    the standard ``https_proxy``/``http_proxy`` env vars, like the
    reference's withProxy :123-178), tarball instead of zipball, an
    optional ``sha256`` pin on the downloaded archive (supply-chain
    guard the reference lacks), and personalization rewrites ``MyApp``
    app names in engine.json rather than renaming Scala packages.

    ``ref`` picks a tag by name; empty means the latest tag (the
    reference prompts; a CLI flag replaces the prompt). Returns the
    directory. Offline installs keep working through the packaged
    scaffolds (template_get).
    """
    import hashlib
    import io
    import tarfile
    import urllib.request

    if "/" not in repo:
        raise KeyError(
            f"{repo!r} is not a remote template (user/repo); packaged "
            f"templates: {[t.name for t in TEMPLATES]}"
        )
    base = (base_url or GITHUB_API).rstrip("/")

    def fetch(url: str) -> bytes:
        req = urllib.request.Request(
            url,
            headers={
                "User-Agent": f"predictionio_tpu/{__version__}",
                "Accept": "application/vnd.github+json",
            },
        )
        # urlopen's default opener honors http(s)_proxy env vars
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read()

    tags = json.loads(fetch(f"{base}/repos/{repo}/tags").decode("utf-8"))
    if not tags:
        raise ValueError(f"repository {repo} has no tags to install from")
    if ref:
        matches = [t for t in tags if t.get("name") == ref]
        if not matches:
            raise ValueError(
                f"tag {ref!r} not found in {repo}; available: "
                f"{[t.get('name') for t in tags][:10]}"
            )
        tag = matches[0]
    else:
        tag = tags[0]  # GitHub orders newest-first (Template.scala:258)
    archive = fetch(
        tag.get("tarball_url")
        or f"{base}/repos/{repo}/tarball/{tag['name']}"
    )
    if sha256:
        got = hashlib.sha256(archive).hexdigest()
        if got != sha256.lower():
            raise ValueError(
                f"checksum mismatch for {repo}@{tag['name']}: "
                f"expected {sha256}, got {got}"
            )

    os.makedirs(directory, exist_ok=False)
    try:
        with tarfile.open(fileobj=io.BytesIO(archive), mode="r:*") as tf:
            members = tf.getmembers()
            # GitHub tarballs nest everything under <user>-<repo>-<sha>/;
            # strip that top-level component like the reference strips the
            # zip's base dir (Template.scala:358-376)
            for m in members:
                parts = m.name.split("/", 1)
                if len(parts) < 2 or not parts[1]:
                    continue
                m.name = parts[1]
                try:
                    # filter="data" rejects path traversal, links, devices
                    tf.extract(m, directory, filter="data")
                except tarfile.FilterError:
                    logger.warning(
                        "skipping unsafe archive member %r from %s",
                        m.name, repo,
                    )

        _personalize_engine_json(directory, app_name)
        if not verify_template_min_version(directory):
            raise ValueError(
                f"template {repo}@{tag['name']} requires a newer "
                "predictionio_tpu (template.json pio.version.min)"
            )
    except BaseException:
        # a failed install (corrupt archive, min-version gate) must not
        # leave a half-populated directory that makes every retry die in
        # os.makedirs(exist_ok=False)
        import shutil

        shutil.rmtree(directory, ignore_errors=True)
        raise
    return directory


def _personalize_engine_json(directory: str, app_name: str) -> None:
    """Rewrite MyApp placeholders in the fetched engine.json (the
    reference personalizes package names and appName the same way,
    Template.scala:382-411)."""
    path = os.path.join(directory, "engine.json")
    if not os.path.exists(path) or app_name == "MyApp":
        return
    with open(path) as f:
        text = f.read()
    with open(path, "w") as f:
        f.write(text.replace('"MyApp"', json.dumps(app_name)))


def verify_template_min_version(directory: str) -> bool:
    """Reference verifyTemplateMinVersion (Template.scala:417-429)."""
    path = os.path.join(directory, "template.json")
    if not os.path.exists(path):
        return True
    with open(path) as f:
        meta = json.load(f)
    min_version = (
        meta.get("pio", {}).get("version", {}).get("min", "0")
    )
    return version_gte(__version__, min_version)
