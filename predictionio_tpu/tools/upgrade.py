"""Upgrade check — `pio upgrade` / the deploy-time daily check
(reference console/Console.scala:1130 `upgrade` command and
WorkflowUtils.scala:386-406 UpgradeCheckRunner, which phones home to
check for a newer release; CreateServer.scala:253-260 runs it daily).

The check is best-effort and never blocks work: any network failure —
including the fully-offline case — reports "could not check" and
returns None. The endpoint is injectable for tests and air-gapped
mirrors (PIO_UPGRADE_URL)."""

from __future__ import annotations

import json
import logging
import os
from typing import Optional

from predictionio_tpu import __version__
from predictionio_tpu.utils.version import version_lt

logger = logging.getLogger(__name__)

DEFAULT_URL = "https://pypi.org/pypi/predictionio-tpu/json"


def latest_version(url: str = "", timeout: float = 5.0) -> Optional[str]:
    """The newest released version, or None when unreachable/unparsable."""
    import urllib.request

    url = url or os.environ.get("PIO_UPGRADE_URL") or DEFAULT_URL
    try:
        req = urllib.request.Request(
            url, headers={"User-Agent": f"predictionio_tpu/{__version__}"}
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            payload = json.loads(resp.read().decode("utf-8"))
        # accept both the PyPI JSON shape and a bare {"version": "..."}
        # mirror; anything else (list, string, non-dict info) is
        # "unparsable", not an exception — the check must never fail
        info = payload.get("info") if isinstance(payload, dict) else None
        source = info if isinstance(info, dict) else payload
        version = source.get("version") if isinstance(source, dict) else None
    except Exception as e:  # offline, DNS, TLS, bad JSON — all non-fatal
        logger.debug("upgrade check unreachable: %s", e)
        return None
    return version if isinstance(version, str) else None


def check_for_upgrade(url: str = "", timeout: float = 5.0) -> str:
    """One-line, human-readable upgrade status."""
    latest = latest_version(url, timeout)
    if latest is None:
        return (
            f"predictionio_tpu {__version__} — could not check for "
            "upgrades (offline?)"
        )
    if version_lt(__version__, latest):
        return (
            f"predictionio_tpu {__version__} — a newer version {latest} "
            "is available"
        )
    return f"predictionio_tpu {__version__} is up to date"
