"""Event export/import as JSON lines.

Capability parity with the reference export/import jobs
(tools/src/main/scala/io/prediction/tools/export/EventsToFile.scala:39-104
— PEvents.find -> json4s strings -> text file; imprt/FileToEvents.scala:
84-95 — textFile -> read[Event] -> PEvents.write). One event per line in
the API JSON format, so exports round-trip through import and are
compatible with event-server payload shapes.
"""

from __future__ import annotations

import json
import logging
from typing import Optional

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import Storage, get_storage
from predictionio_tpu.data.store import app_name_to_id

logger = logging.getLogger(__name__)


def events_to_file(
    app_name: str,
    path: str,
    channel_name: Optional[str] = None,
    storage: Optional[Storage] = None,
) -> int:
    """Export all events of an app (channel) to a JSON-lines file.
    Returns the number of events written."""
    storage = storage or get_storage()
    app_id, channel_id = app_name_to_id(app_name, channel_name, storage)
    n = 0
    with open(path, "w") as f:
        for event in storage.get_p_events().find(
            app_id=app_id, channel_id=channel_id
        ):
            f.write(json.dumps(event.to_json()) + "\n")
            n += 1
    logger.info("exported %d events of app %s to %s", n, app_name, path)
    return n


def file_to_events(
    app_name: str,
    path: str,
    channel_name: Optional[str] = None,
    storage: Optional[Storage] = None,
) -> int:
    """Import events from a JSON-lines file. Returns the number inserted."""
    storage = storage or get_storage()
    app_id, channel_id = app_name_to_id(app_name, channel_name, storage)
    events = []
    with open(path) as f:
        for line_no, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                events.append(Event.from_json(json.loads(line)))
            except Exception as e:
                raise ValueError(
                    f"{path}:{line_no}: invalid event: {e}"
                ) from e
    storage.get_p_events().write(events, app_id, channel_id)
    logger.info("imported %d events into app %s", len(events), app_name)
    return len(events)
