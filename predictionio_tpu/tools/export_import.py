"""Event export/import as JSON lines or Parquet.

Capability parity with the reference export/import jobs
(tools/src/main/scala/io/prediction/tools/export/EventsToFile.scala:39-104
— PEvents.find -> json4s strings -> text file OR Parquet via SQLContext
:85-100; imprt/FileToEvents.scala:84-95 — textFile -> read[Event] ->
PEvents.write). JSON-lines writes one event per line in the API JSON
format, so exports round-trip through import and are compatible with
event-server payload shapes. Parquet writes a columnar file (one column
per event field, timestamps at full microsecond precision, properties as
a JSON-encoded string column) via pyarrow — gated: a clear error tells
the user to install pyarrow when the optional dependency is absent.
Import auto-detects the format from the file's magic bytes.
"""

from __future__ import annotations

import json
import logging
from typing import List, Optional

from predictionio_tpu.data.event import DataMap, Event, parse_iso8601
from predictionio_tpu.data.storage import Storage, get_storage
from predictionio_tpu.data.store import app_name_to_id

logger = logging.getLogger(__name__)

FORMATS = ("json", "parquet")


def _require_pyarrow():
    try:
        import pyarrow
        import pyarrow.parquet
    except ImportError as e:  # pragma: no cover - image has pyarrow
        raise RuntimeError(
            "the parquet format requires the optional pyarrow dependency "
            "(pip install pyarrow); use --format json instead"
        ) from e
    return pyarrow, pyarrow.parquet


def events_to_file(
    app_name: str,
    path: str,
    channel_name: Optional[str] = None,
    storage: Optional[Storage] = None,
    format: str = "json",
) -> int:
    """Export all events of an app (channel) to a JSON-lines or Parquet
    file (reference EventsToFile.scala:85-100 offers the same choice).
    Returns the number of events written."""
    if format not in FORMATS:
        raise ValueError(f"unknown export format {format!r}; pick {FORMATS}")
    storage = storage or get_storage()
    app_id, channel_id = app_name_to_id(app_name, channel_name, storage)
    le = storage.get_p_events()
    if format == "parquet" and hasattr(le, "iter_export_pages"):
        # split export: row-store events through the generic batch
        # writer, bulk pages AND compacted segments as vectorized column
        # batches (exporting 20M events must not build 20M Event objects
        # any more than importing them does). Segment groups carry the
        # ORIGINAL event ids + creation times, so the import side can
        # re-seal them as segments — the near-zero-copy exchange.
        import itertools

        column_groups = le.iter_export_pages(app_id, channel_id)
        if hasattr(le, "iter_export_segments"):
            column_groups = itertools.chain(
                column_groups, le.iter_export_segments(app_id, channel_id)
            )
        n = _write_parquet(
            path,
            le.iter_row_events(app_id, channel_id),
            page_columns=column_groups,
        )
        logger.info(
            "exported %d events of app %s to %s (parquet, columnar "
            "pages + segments)", n, app_name, path,
        )
        return n
    events_iter = le.find(app_id=app_id, channel_id=channel_id)
    if format == "parquet":
        n = _write_parquet(path, events_iter)
    else:
        n = 0
        with open(path, "w") as f:
            for event in events_iter:
                f.write(json.dumps(event.to_json()) + "\n")
                n += 1
    logger.info(
        "exported %d events of app %s to %s (%s)", n, app_name, path, format
    )
    return n


def file_to_events(
    app_name: str,
    path: str,
    channel_name: Optional[str] = None,
    storage: Optional[Storage] = None,
) -> int:
    """Import events from a JSON-lines or Parquet file (auto-detected by
    the Parquet magic bytes). Returns the number inserted."""
    storage = storage or get_storage()
    app_id, channel_id = app_name_to_id(app_name, channel_name, storage)
    with open(path, "rb") as f:
        is_parquet = f.read(4) == b"PAR1"
    if is_parquet:
        # qualify and import PER ROW GROUP: the split exporter writes
        # row events and each bulk page as separate groups, so a mixed
        # file's homogeneous page groups still take the bulk path while
        # only the heterogeneous groups fall back to per-event reads —
        # and peak memory is a couple of groups, not the file. Reads +
        # qualification run in a prefetch thread PIPELINED against the
        # inserts (sqlite releases the GIL during its C work), so the
        # re-import wall clock is ~max(read+qualify, insert) instead of
        # their sum — the remaining gap to a native bulk import.
        import queue
        import threading

        _, pq = _require_pyarrow()
        pf = pq.ParquetFile(path)
        total = bulk = 0
        le = storage.get_p_events()
        q: "queue.Queue" = queue.Queue(maxsize=2)
        stop = threading.Event()

        def produce():
            try:
                for g in range(pf.num_row_groups):
                    if stop.is_set():
                        return
                    table = pf.read_row_group(g)
                    try:
                        prepared = _columnar_import_qualify(table)
                    except Exception as e:
                        # best-effort over possibly-foreign files: any
                        # unexpected column type / cast error means
                        # "does not qualify" -> generic reader
                        logger.debug(
                            "columnar import path disqualified: %s", e
                        )
                        prepared = None
                    q.put(("group", table, prepared))
                q.put(("done", None, None))
            except BaseException as e:  # surfaced by the consumer loop
                q.put(("error", e, None))

        producer = threading.Thread(target=produce, daemon=True)
        producer.start()
        try:
            while True:
                kind, table, prepared = q.get()
                if kind == "done":
                    break
                if kind == "error":
                    raise table
                if prepared is not None and "event_ids" in prepared:
                    # segment-export group (real ids preserved): re-seal
                    # it directly as a segment when the backend has the
                    # tier AND none of the sampled ids already exist —
                    # re-importing into the source app must stay
                    # idempotent, which only the keyed generic path is
                    n = _import_segment_group(
                        le, app_id, channel_id, prepared
                    )
                    if n is None:
                        group_events = _events_from_table(table)
                        le.write(group_events, app_id, channel_id)
                        n = len(group_events)
                    else:
                        bulk += n
                elif prepared is not None:
                    # the WRITE stays outside the producer's qualify
                    # net: a failed/ambiguous bulk write must surface,
                    # not silently fall through to the generic reader
                    # and double-import whatever already landed
                    n = le.insert_columns_encoded(
                        app_id, channel_id, **prepared
                    )
                    bulk += n
                else:
                    group_events = _events_from_table(table)
                    le.write(group_events, app_id, channel_id)
                    n = len(group_events)
                total += n
        finally:
            # a failed insert must not strand the producer on the
            # bounded queue (leaking the thread, the open file, and
            # buffered tables): signal it, drain, and join
            stop.set()
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            producer.join(timeout=30)
        logger.info(
            "imported %d events into app %s (%d via the columnar bulk "
            "path)", total, app_name, bulk,
        )
        return total
    else:
        events = []
        with open(path) as f:
            for line_no, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(Event.from_json(json.loads(line)))
                except Exception as e:
                    raise ValueError(
                        f"{path}:{line_no}: invalid event: {e}"
                    ) from e
    storage.get_p_events().write(events, app_id, channel_id)
    logger.info("imported %d events into app %s", len(events), app_name)
    return len(events)


def _import_segment_group(le, app_id, channel_id, prepared):
    """Land a real-id column group as a sealed segment (near-zero-copy
    import), or return None to route it through the generic reader:
    when the backend has no segment tier, or when any sampled id
    already exists here (idempotent re-import needs the keyed path —
    the segment tier is append-only)."""
    insert_segment = getattr(le, "insert_segment_encoded", None)
    if insert_segment is None:
        return None
    ids = prepared["event_ids"]
    probe = {str(ids[0]), str(ids[len(ids) // 2]), str(ids[-1])}
    try:
        for eid in probe:
            if le.get(eid, app_id, channel_id) is not None:
                return None
        return insert_segment(app_id, channel_id, **prepared)
    except Exception:
        logger.warning(
            "segment import path failed; falling back to the generic "
            "reader", exc_info=True,
        )
        return None


def _columnar_import_qualify(table):
    """Qualify a HOMOGENEOUS parquet row group for the bulk path: one
    event name, one entity/target type pair, no tags/prId, event ids
    absent or page-synthetic (real ids must be preserved, and only the
    generic reader's keyed inserts stay idempotent across re-imports),
    millisecond-representable event times, and every property bag
    exactly ``{"<prop>": <number>}`` with a shared key — the shape
    bulk-rating exports have (or the typed propKey/propValue sidecar the
    exporter writes). Qualified groups route through
    LEvents.insert_columns (binary event pages on sqlite; packed columns
    over the gateway wire) so a 20M-event import takes seconds, not the
    minutes of the one-Event-object-per-row path. Returns None when the
    group does not qualify — heterogeneous events, sub-millisecond
    timestamps (the page store keeps ms; the bulk path must not truncate
    what the generic reader round-trips), empty/varied property bags —
    and raises on surprising column types (the caller treats any raise
    as "does not qualify" too). Checks are vectorized pyarrow compute,
    so disqualifying a large mixed file is cheap."""
    import re as _re

    import numpy as np

    pa, _ = _require_pyarrow()
    import pyarrow.compute as pc

    n = table.num_rows
    if n == 0:
        return None
    cols = {name: table.column(name) for name in table.column_names}
    required = {
        "event", "entityType", "entityId", "targetEntityType",
        "targetEntityId", "properties", "eventTime",
    }
    if not required <= set(cols):
        return None

    def single_value(name):
        uniq = pc.unique(cols[name].combine_chunks())
        if len(uniq) != 1 or not uniq[0].is_valid:
            return None
        return uniq[0].as_py()

    event = single_value("event")
    entity_type = single_value("entityType")
    target_entity_type = single_value("targetEntityType")
    if not event or event.startswith("$") or not entity_type:
        return None
    if not target_entity_type:
        return None
    for name in ("entityId", "targetEntityId", "eventTime"):
        if pc.sum(pc.cast(pc.is_null(cols[name]), pa.int64())).as_py():
            return None
    # event ids: absent or page-synthetic ("pg-<page>-<idx>" —
    # source-local positional handles with no meaning in another store)
    # keep the plain bulk path. A group where EVERY row carries a real,
    # unique, bounded-width id is a SEGMENT export: qualify it with the
    # ids (and creation times below) preserved, so the import side can
    # re-seal it as a segment. Mixed/partial ids take the generic path,
    # which preserves them row by row and stays idempotent across
    # re-imports (INSERT OR REPLACE keyed on id).
    event_ids = None
    if "eventId" in cols:
        ids = cols["eventId"].combine_chunks()
        n_real = pc.sum(pc.cast(pc.is_valid(ids), pa.int64())).as_py() or 0
        if n_real:
            synthetic = pc.match_substring_regex(ids, "^pg-[0-9]+-[0-9]+$")
            ok = pc.sum(pc.cast(synthetic, pa.int64())).as_py() or 0
            if ok != n_real and not (ok == 0 and n_real == n):
                return None
            if ok == 0 and n_real == n:
                from predictionio_tpu.data.storage.segments import (
                    MAX_ID_BYTES,
                )

                ids_np = ids.to_numpy(zero_copy_only=False)
                if len(np.unique(ids_np)) != n or max(
                    len(str(i).encode("utf-8")) for i in ids_np
                ) > MAX_ID_BYTES:
                    return None
                event_ids = ids_np
    if "prId" in cols and pc.sum(
        pc.cast(pc.is_valid(cols["prId"]), pa.int64())
    ).as_py():
        return None
    if "tags" in cols:
        tags = cols["tags"].combine_chunks()
        if hasattr(tags, "values"):
            # O(1): a list column's flattened child holds every element
            # of every list — zero length means no event carries tags
            # (a per-row list_value_length scan cost 0.3 s per 1M rows)
            if len(tags.values):
                return None
        else:
            lens = pc.fill_null(pc.list_value_length(tags), 0)
            if pc.sum(lens).as_py():
                return None

    # typed sidecar columns first (written by this package's own page
    # exporter): the property key/value arrive as real columns, so the
    # regex re-parse of 20M JSON strings — the dominant re-import cost,
    # and JSON this very exporter rendered — is skipped. A file carrying
    # a fully-valid sidecar is opting into the documented bulk form; the
    # `properties` JSON stays in the file for generic readers.
    prop_key = values = None
    if "propKey" in cols and "propValue" in cols:
        key = single_value("propKey")
        pv = cols["propValue"].combine_chunks()
        props_col = cols["properties"].combine_chunks()
        if (
            key
            and not pc.sum(pc.cast(pc.is_null(pv), pa.int64())).as_py()
            # null bags would be rejected by the regex path; the sidecar
            # must not be laxer (same vectorized cost, ~0.01 s/M)
            and not pc.sum(
                pc.cast(pc.is_null(props_col), pa.int64())
            ).as_py()
        ):
            # Consistency probes against the authoritative properties
            # JSON: a file whose bags were edited after export (or an
            # inconsistent foreign writer) falls through to the
            # fully-validating regex path / generic reader instead of
            # silently importing divergent sidecar values.
            def bag_matches(j: int) -> bool:
                try:
                    parsed = json.loads(props_col[j].as_py())
                except (ValueError, TypeError):
                    return False
                if not (
                    isinstance(parsed, dict)
                    and set(parsed) == {key}
                    and isinstance(parsed[key], (int, float))
                    and not isinstance(parsed[key], bool)
                ):
                    return False
                p = np.float32(parsed[key])
                v = np.float32(pv[j].as_py())
                return bool(p == v) or bool(np.isnan(p) and np.isnan(v))

            def sidecar_sample_agrees(pv_np: "np.ndarray") -> bool:
                # Vectorized sample validation (ADVICE.md): regex-parse
                # a bounded strided SAMPLE of the properties JSON —
                # always including the rows holding the sidecar's min
                # and max, so the cheap aggregates (non-null count was
                # checked above; extrema here; elementwise equality
                # implies the sample sums agree) cannot diverge
                # unnoticed. A bag altered ONLY at unsampled interior
                # rows still slips through — full validation is exactly
                # the 20M-string reparse this path exists to skip — but
                # bulk edits and shifted/scaled value columns now fail
                # qualification at ~4k parses per row group.
                idx = np.linspace(
                    0, n - 1, num=min(n, 4096), dtype=np.int64
                )
                finite = np.isfinite(pv_np)
                if finite.any():
                    extremes = np.array(
                        [
                            int(np.nanargmin(np.where(finite, pv_np, np.nan))),
                            int(np.nanargmax(np.where(finite, pv_np, np.nan))),
                        ],
                        dtype=np.int64,
                    )
                    idx = np.concatenate([idx, extremes])
                idx = np.unique(idx)
                pattern = (
                    '^\\{"'
                    + _re.escape(key)
                    + '": (?P<v>-?[0-9]+(?:\\.[0-9]+)?'
                    + "(?:[eE][-+]?[0-9]+)?)\\}$"
                )
                sampled = props_col.take(pa.array(idx))
                extracted = pc.extract_regex(sampled, pattern)
                nulls = pc.is_null(extracted).to_numpy(
                    zero_copy_only=False
                )
                if nulls.any():
                    # the numeric regex can't express NaN/±Infinity
                    # (json.dumps renders the bare tokens); those few
                    # rows fall back to the exact json parse instead of
                    # disqualifying a legitimate export
                    if not all(
                        bag_matches(int(j)) for j in idx[nulls]
                    ):
                        return False
                parsed = np.asarray(
                    pc.fill_null(
                        pc.struct_field(extracted, "v"), "0"
                    ).to_numpy(zero_copy_only=False),
                    dtype="U32",
                ).astype(np.float32)
                sample = pv_np[idx]
                ok = (
                    (parsed == sample)
                    | (np.isnan(parsed) & np.isnan(sample))
                    | nulls  # already validated row-exactly above
                )
                return bool(ok.all())

            pv_np = pv.to_numpy(zero_copy_only=False).astype(np.float32)
            if all(
                bag_matches(j) for j in {0, n // 2, n - 1}
            ) and sidecar_sample_agrees(pv_np):
                prop_key = key
                values = pv_np

    if values is None:
        # property bags: all exactly {"<key>": <number>} sharing one key.
        # All-empty bags fall back too — the bulk form would have to
        # invent a value where the generic reader faithfully stores an
        # empty bag.
        props = cols["properties"].combine_chunks()
        first = next((v.as_py() for v in props if v.is_valid), None)
        if first is None:
            return None
        parsed = json.loads(first)
        if not (
            isinstance(parsed, dict)
            and len(parsed) == 1
            and isinstance(next(iter(parsed.values())), (int, float))
            and not isinstance(next(iter(parsed.values())), bool)
        ):
            return None
        prop_key = next(iter(parsed))
        if pc.sum(pc.cast(pc.is_null(props), pa.int64())).as_py():
            return None  # mixed empty/non-empty bags: fall back
        pattern = (
            '^\\{"'
            + _re.escape(prop_key)
            + '": (?P<v>-?[0-9]+(?:\\.[0-9]+)?(?:[eE][-+]?[0-9]+)?)\\}$'
        )
        extracted = pc.extract_regex(props, pattern)
        if pc.sum(pc.cast(pc.is_null(extracted), pa.int64())).as_py():
            return None  # some bag deviates: fall back
        values = np.asarray(
            pc.struct_field(extracted, "v").to_numpy(zero_copy_only=False),
            dtype="U32",
        ).astype(np.float32)

    times = cols["eventTime"].combine_chunks()
    if not pa.types.is_timestamp(times.type):
        return None
    # safe cast: sub-millisecond timestamps raise -> caught by the
    # wrapper -> generic path keeps their full precision
    times_ms = (
        pc.cast(times, pa.timestamp("ms", tz="UTC"))
        .cast(pa.int64())
        .to_numpy(zero_copy_only=False)
        .astype(np.int64)
    )
    # ids leave as (distinct names, int32 codes) via arrow's C++
    # dictionary_encode — materializing 20M Python id strings and
    # re-factorizing them in numpy (encode_strings) cost ~1/4 of the
    # whole re-import; the dictionary path hands insert_columns_encoded
    # exactly the form the page store wants
    def encode(name):
        enc = pc.dictionary_encode(cols[name].combine_chunks())
        return (
            enc.dictionary.to_numpy(zero_copy_only=False),
            enc.indices.to_numpy(zero_copy_only=False).astype(
                np.int32, copy=False
            ),
        )

    e_names, e_codes = encode("entityId")
    g_names, g_codes = encode("targetEntityId")
    prepared = dict(
        event=event,
        entity_type=entity_type,
        target_entity_type=target_entity_type,
        entity_names=e_names,
        entity_codes=e_codes,
        target_names=g_names,
        target_codes=g_codes,
        values=values,
        value_property=prop_key,
        event_times_ms=times_ms,
    )
    if event_ids is not None:
        # a real-id (segment) group must also round-trip its creation
        # times to re-seal losslessly; sub-ms creation times fall back
        # to the generic reader via the safe-cast raise
        ctimes = cols.get("creationTime")
        if ctimes is None:
            return None
        ctimes = ctimes.combine_chunks()
        if not pa.types.is_timestamp(ctimes.type):
            return None
        prepared["event_ids"] = event_ids
        prepared["creation_times_ms"] = (
            pc.cast(ctimes, pa.timestamp("ms", tz="UTC"))
            .cast(pa.int64())
            .to_numpy(zero_copy_only=False)
            .astype(np.int64)
        )
    return prepared


# --- parquet columnar layout ---

_PARQUET_STRING_COLS = (
    # (column name, Event attribute)
    ("eventId", "event_id"),
    ("event", "event"),
    ("entityType", "entity_type"),
    ("entityId", "entity_id"),
    ("targetEntityType", "target_entity_type"),
    ("targetEntityId", "target_entity_id"),
    ("prId", "pr_id"),
)


_PARQUET_BATCH_ROWS = 65_536


def _page_columns_to_table(pa, schema, ts, page: dict):
    """One bulk page -> one pyarrow table, all columns vectorized.

    Values render as %.9g (round-trips float32 exactly) inside the
    single-key JSON shape the columnar importer recognizes, so a page
    export re-imports through the bulk path byte-faithfully."""
    import numpy as np

    n = len(page["values"])
    const = lambda v: pa.array([v] * n, type=pa.string())  # noqa: E731
    values = page["values"]
    vals_str = np.char.mod("%.9g", values)
    bad = np.nonzero(~np.isfinite(values))[0]
    if bad.size:
        # the fixed-width U array is sized by the widest finite rendering;
        # "-Infinity" (9 chars) would silently truncate without widening
        if vals_str.dtype.itemsize < np.dtype("U9").itemsize:
            vals_str = vals_str.astype("U9")
        for j in bad:  # rare: render the tokens json.loads accepts
            v = float(values[j])
            vals_str[j] = (
                "NaN" if v != v else ("Infinity" if v > 0 else "-Infinity")
            )
    # the key goes through json.dumps so quotes/backslashes/control
    # chars escape correctly. Empty-prop rows (segment groups of
    # propertyless events) render an empty bag.
    if page["prop"]:
        props = np.char.add(
            np.char.add("{%s: " % json.dumps(page["prop"]), vals_str), "}"
        )
        props = props.tolist()
    else:
        props = [None] * n
    times = pa.array(page["times_ms"] * 1000, type=pa.int64()).cast(ts)
    ctimes = (
        pa.array(
            np.asarray(page["creation_times_ms"], np.int64) * 1000,
            type=pa.int64(),
        ).cast(ts)
        if page.get("creation_times_ms") is not None
        else times
    )
    cols = {
        "eventId": pa.array(page["event_ids"], type=pa.string()),
        "event": const(page["event"]),
        "entityType": const(page["entity_type"]),
        # pyarrow converts numpy str arrays directly (no per-element
        # Python round trip); np.str_ is a str subclass
        "entityId": pa.array(
            np.asarray(page["entity_ids"], object), type=pa.string()
        ),
        "targetEntityType": const(page["target_entity_type"]),
        "targetEntityId": pa.array(
            np.asarray(page["target_ids"], object), type=pa.string()
        ),
        "prId": pa.array([None] * n, type=pa.string()),
        "properties": pa.array(props, type=pa.string()),
        "tags": pa.array([[]] * n, type=pa.list_(pa.string())),
        "eventTime": times,
        "creationTime": ctimes,
        "propKey": const(page["prop"]) if page["prop"] else pa.array(
            [None] * n, type=pa.string()
        ),
        "propValue": pa.array(
            np.asarray(values, np.float64), type=pa.float64()
        ),
    }
    return pa.table(cols, schema=schema)


def _write_parquet(path: str, events, page_columns=None) -> int:
    """Streams row-group batches through a ParquetWriter — like the JSON
    path, peak memory is one batch, not the whole event history.
    ``page_columns`` (bulk pages as decoded numpy columns) append as
    vectorized tables after the row events."""
    import itertools

    pa, pq = _require_pyarrow()
    ts = pa.timestamp("us", tz="UTC")
    schema = pa.schema(
        [pa.field(name, pa.string()) for name, _ in _PARQUET_STRING_COLS]
        + [
            # properties keep their JSON shape in one string column: the
            # bag is schemaless across events, so flattening to columns
            # would make the file schema depend on the data (the reference
            # lets SQLContext infer a merged schema, EventsToFile.scala:
            # 93-97; a JSON column round-trips losslessly without that
            # inference machinery)
            pa.field("properties", pa.string()),
            pa.field("tags", pa.list_(pa.string())),
            # full microsecond precision — better than the API JSON's
            # millisecond rendering
            pa.field("eventTime", ts),
            pa.field("creationTime", ts),
            # typed sidecar for bulk-page groups: the single property's
            # key + value as real columns. The JSON `properties` column
            # stays authoritative for generic readers; the sidecar lets
            # re-import skip regex-parsing 20M JSON strings this very
            # exporter rendered (the round-4 import/export asymmetry).
            # Null on row-event groups.
            pa.field("propKey", pa.string()),
            pa.field("propValue", pa.float64()),
        ]
    )
    events = iter(events)
    n = 0
    with pq.ParquetWriter(path, schema) as writer:
        while True:
            batch = list(itertools.islice(events, _PARQUET_BATCH_ROWS))
            if not batch and n > 0:
                break
            cols = {
                name: pa.array(
                    [getattr(e, attr) for e in batch], type=pa.string()
                )
                for name, attr in _PARQUET_STRING_COLS
            }
            cols["properties"] = pa.array(
                [
                    json.dumps(e.properties.to_json())
                    if len(e.properties)
                    else None
                    for e in batch
                ],
                type=pa.string(),
            )
            cols["tags"] = pa.array(
                [list(e.tags) for e in batch], type=pa.list_(pa.string())
            )
            cols["eventTime"] = pa.array(
                [e.event_time for e in batch], type=ts
            )
            cols["creationTime"] = pa.array(
                [e.creation_time for e in batch], type=ts
            )
            cols["propKey"] = pa.array([None] * len(batch), type=pa.string())
            cols["propValue"] = pa.array(
                [None] * len(batch), type=pa.float64()
            )
            writer.write_table(pa.table(cols, schema=schema))
            n += len(batch)
            if len(batch) < _PARQUET_BATCH_ROWS:
                break
        if page_columns is not None:
            for page in page_columns:
                writer.write_table(
                    _page_columns_to_table(pa, schema, ts, page)
                )
                n += len(page["values"])
    return n


def _read_parquet(path: str) -> List[Event]:
    _, pq = _require_pyarrow()
    return _events_from_table(pq.read_table(path))


def _events_from_table(table) -> List[Event]:
    import datetime as _dt

    rows = table.to_pylist()
    events = []
    for row in rows:
        props = row.get("properties")
        kwargs = {
            attr: row.get(name) for name, attr in _PARQUET_STRING_COLS
        }
        for time_field in ("eventTime", "creationTime"):
            v = row.get(time_field)
            if isinstance(v, str):  # files written by other tools
                v = parse_iso8601(v)
            elif isinstance(v, _dt.datetime) and v.tzinfo is None:
                v = v.replace(tzinfo=_dt.timezone.utc)
            row[time_field] = v
        events.append(
            Event(
                properties=DataMap(json.loads(props) if props else None),
                event_time=row["eventTime"],
                tags=tuple(row.get("tags") or ()),
                creation_time=row["creationTime"],
                **kwargs,
            )
        )
    return events
