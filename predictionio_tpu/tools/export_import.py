"""Event export/import as JSON lines or Parquet.

Capability parity with the reference export/import jobs
(tools/src/main/scala/io/prediction/tools/export/EventsToFile.scala:39-104
— PEvents.find -> json4s strings -> text file OR Parquet via SQLContext
:85-100; imprt/FileToEvents.scala:84-95 — textFile -> read[Event] ->
PEvents.write). JSON-lines writes one event per line in the API JSON
format, so exports round-trip through import and are compatible with
event-server payload shapes. Parquet writes a columnar file (one column
per event field, timestamps at full microsecond precision, properties as
a JSON-encoded string column) via pyarrow — gated: a clear error tells
the user to install pyarrow when the optional dependency is absent.
Import auto-detects the format from the file's magic bytes.
"""

from __future__ import annotations

import json
import logging
from typing import List, Optional

from predictionio_tpu.data.event import DataMap, Event, parse_iso8601
from predictionio_tpu.data.storage import Storage, get_storage
from predictionio_tpu.data.store import app_name_to_id

logger = logging.getLogger(__name__)

FORMATS = ("json", "parquet")


def _require_pyarrow():
    try:
        import pyarrow
        import pyarrow.parquet
    except ImportError as e:  # pragma: no cover - image has pyarrow
        raise RuntimeError(
            "the parquet format requires the optional pyarrow dependency "
            "(pip install pyarrow); use --format json instead"
        ) from e
    return pyarrow, pyarrow.parquet


def events_to_file(
    app_name: str,
    path: str,
    channel_name: Optional[str] = None,
    storage: Optional[Storage] = None,
    format: str = "json",
) -> int:
    """Export all events of an app (channel) to a JSON-lines or Parquet
    file (reference EventsToFile.scala:85-100 offers the same choice).
    Returns the number of events written."""
    if format not in FORMATS:
        raise ValueError(f"unknown export format {format!r}; pick {FORMATS}")
    storage = storage or get_storage()
    app_id, channel_id = app_name_to_id(app_name, channel_name, storage)
    events_iter = storage.get_p_events().find(
        app_id=app_id, channel_id=channel_id
    )
    if format == "parquet":
        n = _write_parquet(path, events_iter)
    else:
        n = 0
        with open(path, "w") as f:
            for event in events_iter:
                f.write(json.dumps(event.to_json()) + "\n")
                n += 1
    logger.info(
        "exported %d events of app %s to %s (%s)", n, app_name, path, format
    )
    return n


def file_to_events(
    app_name: str,
    path: str,
    channel_name: Optional[str] = None,
    storage: Optional[Storage] = None,
) -> int:
    """Import events from a JSON-lines or Parquet file (auto-detected by
    the Parquet magic bytes). Returns the number inserted."""
    storage = storage or get_storage()
    app_id, channel_id = app_name_to_id(app_name, channel_name, storage)
    with open(path, "rb") as f:
        is_parquet = f.read(4) == b"PAR1"
    if is_parquet:
        events = _read_parquet(path)
    else:
        events = []
        with open(path) as f:
            for line_no, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(Event.from_json(json.loads(line)))
                except Exception as e:
                    raise ValueError(
                        f"{path}:{line_no}: invalid event: {e}"
                    ) from e
    storage.get_p_events().write(events, app_id, channel_id)
    logger.info("imported %d events into app %s", len(events), app_name)
    return len(events)


# --- parquet columnar layout ---

_PARQUET_STRING_COLS = (
    # (column name, Event attribute)
    ("eventId", "event_id"),
    ("event", "event"),
    ("entityType", "entity_type"),
    ("entityId", "entity_id"),
    ("targetEntityType", "target_entity_type"),
    ("targetEntityId", "target_entity_id"),
    ("prId", "pr_id"),
)


_PARQUET_BATCH_ROWS = 65_536


def _write_parquet(path: str, events) -> int:
    """Streams row-group batches through a ParquetWriter — like the JSON
    path, peak memory is one batch, not the whole event history."""
    import itertools

    pa, pq = _require_pyarrow()
    ts = pa.timestamp("us", tz="UTC")
    schema = pa.schema(
        [pa.field(name, pa.string()) for name, _ in _PARQUET_STRING_COLS]
        + [
            # properties keep their JSON shape in one string column: the
            # bag is schemaless across events, so flattening to columns
            # would make the file schema depend on the data (the reference
            # lets SQLContext infer a merged schema, EventsToFile.scala:
            # 93-97; a JSON column round-trips losslessly without that
            # inference machinery)
            pa.field("properties", pa.string()),
            pa.field("tags", pa.list_(pa.string())),
            # full microsecond precision — better than the API JSON's
            # millisecond rendering
            pa.field("eventTime", ts),
            pa.field("creationTime", ts),
        ]
    )
    events = iter(events)
    n = 0
    with pq.ParquetWriter(path, schema) as writer:
        while True:
            batch = list(itertools.islice(events, _PARQUET_BATCH_ROWS))
            if not batch and n > 0:
                break
            cols = {
                name: pa.array(
                    [getattr(e, attr) for e in batch], type=pa.string()
                )
                for name, attr in _PARQUET_STRING_COLS
            }
            cols["properties"] = pa.array(
                [
                    json.dumps(e.properties.to_json())
                    if len(e.properties)
                    else None
                    for e in batch
                ],
                type=pa.string(),
            )
            cols["tags"] = pa.array(
                [list(e.tags) for e in batch], type=pa.list_(pa.string())
            )
            cols["eventTime"] = pa.array(
                [e.event_time for e in batch], type=ts
            )
            cols["creationTime"] = pa.array(
                [e.creation_time for e in batch], type=ts
            )
            writer.write_table(pa.table(cols, schema=schema))
            n += len(batch)
            if len(batch) < _PARQUET_BATCH_ROWS:
                break
    return n


def _read_parquet(path: str) -> List[Event]:
    import datetime as _dt

    pa, pq = _require_pyarrow()
    table = pq.read_table(path)
    rows = table.to_pylist()
    events = []
    for row in rows:
        props = row.get("properties")
        kwargs = {
            attr: row.get(name) for name, attr in _PARQUET_STRING_COLS
        }
        for time_field in ("eventTime", "creationTime"):
            v = row.get(time_field)
            if isinstance(v, str):  # files written by other tools
                v = parse_iso8601(v)
            elif isinstance(v, _dt.datetime) and v.tzinfo is None:
                v = v.replace(tzinfo=_dt.timezone.utc)
            row[time_field] = v
        events.append(
            Event(
                properties=DataMap(json.loads(props) if props else None),
                event_time=row["eventTime"],
                tags=tuple(row.get("tags") or ()),
                creation_time=row["creationTime"],
                **kwargs,
            )
        )
    return events
