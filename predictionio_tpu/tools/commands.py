"""App/AccessKey/Channel admin commands.

Capability parity with the reference console handlers
(tools/src/main/scala/io/prediction/tools/console/App.scala:31-478,
AccessKey.scala:26-83) and the admin CommandClient
(tools/.../admin/CommandClient.scala:46-160). These are the shared core
used by both the CLI and the admin REST server.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List, Optional

from predictionio_tpu.data.storage import Storage, get_storage
from predictionio_tpu.data.storage.base import AccessKey, App, Channel

logger = logging.getLogger(__name__)


class CommandError(Exception):
    """A command failed in an expected way (bad input, conflict)."""


@dataclasses.dataclass
class AppDescription:
    app: App
    access_keys: List[AccessKey]
    channels: List[Channel]


class CommandClient:
    def __init__(self, storage: Optional[Storage] = None):
        self.storage = storage or get_storage()

    # --- apps (reference App.scala:31-92 create w/ rollback) ---

    def app_new(
        self,
        name: str,
        app_id: int = 0,
        description: Optional[str] = None,
        access_key: str = "",
    ) -> AppDescription:
        apps = self.storage.get_meta_data_apps()
        if apps.get_by_name(name) is not None:
            raise CommandError(f"App {name} already exists. Aborting.")
        if app_id:
            if apps.get(app_id) is not None:
                raise CommandError(f"App ID {app_id} already exists. Aborting.")
        new_id = apps.insert(App(id=app_id, name=name, description=description))
        if new_id is None:
            raise CommandError("Unable to create new app.")
        try:
            events = self.storage.get_l_events()
            if not events.init(new_id):
                raise CommandError(
                    f"Unable to initialize Event Store for app {name}."
                )
            key = self.storage.get_meta_data_access_keys().insert(
                AccessKey(key=access_key, appid=new_id, events=())
            )
            if key is None:
                raise CommandError("Unable to create new access key.")
        except Exception:
            # rollback the app row (reference App.scala:70-84)
            apps.delete(new_id)
            raise
        app = apps.get(new_id)
        logger.info("created app %s (id %d)", name, new_id)
        return AppDescription(
            app=app,
            access_keys=self.storage.get_meta_data_access_keys().get_by_app_id(
                new_id
            ),
            channels=[],
        )

    def app_list(self) -> List[AppDescription]:
        apps = self.storage.get_meta_data_apps().get_all()
        keys = self.storage.get_meta_data_access_keys()
        channels = self.storage.get_meta_data_channels()
        return [
            AppDescription(
                app=a,
                access_keys=keys.get_by_app_id(a.id),
                channels=channels.get_by_app_id(a.id),
            )
            for a in sorted(apps, key=lambda a: a.name)
        ]

    def app_show(self, name: str) -> AppDescription:
        app = self._app(name)
        return AppDescription(
            app=app,
            access_keys=self.storage.get_meta_data_access_keys().get_by_app_id(
                app.id
            ),
            channels=self.storage.get_meta_data_channels().get_by_app_id(
                app.id
            ),
        )

    def app_delete(self, name: str) -> None:
        """Delete an app, its channels, event data, and access keys
        (reference App.scala delete + CommandClient.futureAppDelete)."""
        app = self._app(name)
        events = self.storage.get_l_events()
        channels = self.storage.get_meta_data_channels()
        for ch in channels.get_by_app_id(app.id):
            if not events.remove(app.id, ch.id):
                raise CommandError(
                    f"Error removing event data of channel {ch.name}."
                )
            channels.delete(ch.id)
        if not events.remove(app.id):
            raise CommandError(f"Error removing event data of app {name}.")
        keys = self.storage.get_meta_data_access_keys()
        for k in keys.get_by_app_id(app.id):
            keys.delete(k.key)
            self._revoke_cached_key(k.key)
        if not self.storage.get_meta_data_apps().delete(app.id):
            raise CommandError(f"Error deleting app {name}.")
        logger.info("deleted app %s", name)

    @staticmethod
    def _revoke_cached_key(key: str) -> None:
        """Invalidate every in-process event server's auth cache so a
        just-deleted key stops authenticating immediately instead of at
        the cache TTL (lazy import: the api layer is optional here)."""
        from predictionio_tpu.api.event_server import invalidate_access_key

        invalidate_access_key(key)

    def app_data_delete(
        self, name: str, channel: Optional[str] = None, all_channels: bool = False
    ) -> None:
        """Wipe (and re-init) event data (reference App.scala dataDelete)."""
        app = self._app(name)
        events = self.storage.get_l_events()
        if channel is not None:
            ch = self._channel(app, channel)
            if not (events.remove(app.id, ch.id) and events.init(app.id, ch.id)):
                raise CommandError(
                    f"Error removing event data of channel {channel}."
                )
            return
        if all_channels:
            for ch in self.storage.get_meta_data_channels().get_by_app_id(app.id):
                if not (events.remove(app.id, ch.id) and events.init(app.id, ch.id)):
                    raise CommandError(
                        f"Error removing event data of channel {ch.name}."
                    )
        if not (events.remove(app.id) and events.init(app.id)):
            raise CommandError(f"Error removing event data of app {name}.")
        logger.info("deleted data of app %s", name)

    # --- channels (reference App.scala:416-478) ---

    def channel_new(self, app_name: str, channel_name: str) -> Channel:
        app = self._app(app_name)
        if not Channel.is_valid_name(channel_name):
            raise CommandError(
                f"Unable to create new channel. Invalid channel name "
                f"{channel_name!r} (allowed: [a-zA-Z0-9-], max 16 chars)."
            )
        channels = self.storage.get_meta_data_channels()
        if any(
            c.name == channel_name for c in channels.get_by_app_id(app.id)
        ):
            raise CommandError(
                f"Channel {channel_name} already exists. Aborting."
            )
        channel_id = channels.insert(
            Channel(id=0, name=channel_name, appid=app.id)
        )
        if channel_id is None:
            raise CommandError("Unable to create new channel.")
        if not self.storage.get_l_events().init(app.id, channel_id):
            channels.delete(channel_id)  # rollback
            raise CommandError(
                f"Unable to initialize Event Store for channel {channel_name}."
            )
        return channels.get(channel_id)

    def channel_delete(self, app_name: str, channel_name: str) -> None:
        app = self._app(app_name)
        ch = self._channel(app, channel_name)
        if not self.storage.get_l_events().remove(app.id, ch.id):
            raise CommandError(
                f"Error removing event data of channel {channel_name}."
            )
        if not self.storage.get_meta_data_channels().delete(ch.id):
            raise CommandError(f"Unable to delete channel {channel_name}.")

    # --- access keys (reference AccessKey.scala:26-83) ---

    def access_key_new(
        self, app_name: str, key: str = "", events: tuple = ()
    ) -> AccessKey:
        app = self._app(app_name)
        keys = self.storage.get_meta_data_access_keys()
        created = keys.insert(AccessKey(key=key, appid=app.id, events=events))
        if created is None:
            raise CommandError("Unable to create new access key.")
        return keys.get(created)

    def access_key_list(self, app_name: Optional[str] = None) -> List[AccessKey]:
        keys = self.storage.get_meta_data_access_keys()
        if app_name is None:
            return sorted(keys.get_all(), key=lambda k: k.appid)
        return keys.get_by_app_id(self._app(app_name).id)

    def access_key_delete(self, key: str) -> None:
        if not self.storage.get_meta_data_access_keys().delete(key):
            raise CommandError(f"Error deleting access key {key}.")
        self._revoke_cached_key(key)

    # --- helpers ---

    def _app(self, name: str) -> App:
        app = self.storage.get_meta_data_apps().get_by_name(name)
        if app is None:
            raise CommandError(f"App {name} does not exist. Aborting.")
        return app

    def _channel(self, app: App, channel_name: str) -> Channel:
        for c in self.storage.get_meta_data_channels().get_by_app_id(app.id):
            if c.name == channel_name:
                return c
        raise CommandError(
            f"Unable to delete channel. Channel {channel_name} doesn't exist."
        )
