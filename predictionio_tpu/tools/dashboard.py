"""Evaluation dashboard on :9000.

Capability parity with the reference Dashboard
(tools/src/main/scala/io/prediction/tools/dashboard/Dashboard.scala:70-141):

  GET /                                      -> HTML index of completed
                                                evaluation instances
  GET /engine_instances/<id>/evaluator_results.txt
  GET /engine_instances/<id>/evaluator_results.html
  GET /engine_instances/<id>/evaluator_results.json
  GET /engine_instances/<id>/local_evaluator_results.json  (CORS variant)
"""

from __future__ import annotations

import datetime as _dt
import html as _html
import logging
import os
from typing import Optional, Tuple

from predictionio_tpu.api.http import JsonHTTPServer
from predictionio_tpu.data.storage import Storage, get_storage

logger = logging.getLogger(__name__)


class DashboardAPI:
    def __init__(self, storage: Optional[Storage] = None):
        self.storage = storage or get_storage()
        self.server_start_time = _dt.datetime.now(_dt.timezone.utc)

    def handle(self, method, path, query=None, body=None, form=None) -> Tuple:
        if method != "GET":
            return 405, {"message": "Method not allowed."}
        parts = [p for p in path.strip("/").split("/") if p]
        if not parts:
            return 200, self._index(), "text/html"
        if parts[0] == "engine_instances" and len(parts) == 3:
            instance_id, resource = parts[1], parts[2]
            instance = (
                self.storage.get_meta_data_evaluation_instances().get(
                    instance_id
                )
            )
            if instance is None:
                return 404, {"message": "Not Found"}
            if resource == "evaluator_results.txt":
                return 200, instance.evaluator_results, "text/plain"
            if resource == "evaluator_results.html":
                return 200, instance.evaluator_results_html, "text/html"
            if resource in (
                "evaluator_results.json",
                "local_evaluator_results.json",
            ):
                # stored pre-rendered; str payloads pass through verbatim
                return 200, instance.evaluator_results_json, "application/json"
        return 404, {"message": "Not Found"}

    def _index(self) -> str:
        instances = (
            self.storage.get_meta_data_evaluation_instances().get_completed()
        )
        rows = "".join(
            "<tr>"
            f"<td>{_html.escape(i.id)}</td>"
            f"<td>{_html.escape(i.evaluation_class)}</td>"
            f"<td>{_html.escape(i.start_time.isoformat())}</td>"
            f"<td>{_html.escape(i.evaluator_results)}</td>"
            f"<td><a href='/engine_instances/{i.id}/evaluator_results.html'>HTML</a> "
            f"<a href='/engine_instances/{i.id}/evaluator_results.json'>JSON</a> "
            f"<a href='/engine_instances/{i.id}/evaluator_results.txt'>TXT</a></td>"
            "</tr>"
            for i in instances
        )
        env_rows = "".join(
            f"<tr><td>{_html.escape(k)}</td><td>{_html.escape(v)}</td></tr>"
            for k, v in sorted(os.environ.items())
            if k.startswith("PIO_")
        )
        return (
            "<!DOCTYPE html><html><head><title>PredictionIO-TPU Dashboard"
            "</title></head><body><h1>Evaluation Dashboard</h1>"
            f"<p>Server started {self.server_start_time.isoformat()}</p>"
            "<table border='1'><tr><th>ID</th><th>Evaluation</th>"
            "<th>Started</th><th>Result</th><th>Links</th></tr>"
            f"{rows}</table>"
            f"<h2>Environment</h2><table>{env_rows}</table>"
            "</body></html>"
        )


class Dashboard(JsonHTTPServer):
    def __init__(
        self,
        ip: str = "localhost",
        port: int = 9000,
        storage: Optional[Storage] = None,
    ):
        self.api = DashboardAPI(storage)
        super().__init__(self.api.handle, ip, port, "Dashboard")


def create_dashboard(
    ip: str = "localhost", port: int = 9000, storage: Optional[Storage] = None
) -> Dashboard:
    """Reference Dashboard.createDashboard (Dashboard.scala:37-68)."""
    return Dashboard(ip=ip, port=port, storage=storage)
