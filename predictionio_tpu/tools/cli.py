"""The ``pio`` console: python -m predictionio_tpu.tools.cli <command>.

Capability parity with the reference pio CLI
(tools/src/main/scala/io/prediction/tools/console/Console.scala:130-1292):

  app new|list|show|delete|data-delete|channel-new|channel-delete
  accesskey new|list|delete
  build                        register the engine manifest
  train                        run the training workflow
  eval                         run an Evaluation (+ params generator)
  deploy                       start the engine query server
  undeploy                     stop a deployed server (HTTP /stop)
  eventserver                  start the Event Server
  adminserver                  start the admin REST server
  dashboard                    start the evaluation dashboard
  export | import              events <-> JSON-lines files
  status                       check storage configuration
  version

Where the reference shells out to spark-submit (RunWorkflow.scala:32,
RunServer.scala:29), commands here run in process: training is a direct
CoreWorkflow call on the JAX runtime, deploy binds the query server in
the foreground. Engines are resolved from the ``engineFactory`` class
path in engine.json (the reference reflects the same field,
WorkflowUtils.scala:63-119).
"""

from __future__ import annotations

import argparse
import dataclasses
import datetime as _dt
import importlib
import json
import logging
import sys
import urllib.request
from typing import Any, List, Optional

from predictionio_tpu import __version__
from predictionio_tpu.tools.commands import CommandClient, CommandError

logger = logging.getLogger(__name__)


# --- reflection (reference WorkflowUtils.getEngine / getEvaluation) ---


def resolve_attr(class_path: str) -> Any:
    """Resolve 'pkg.module.Attr' (or 'pkg.module' exposing a single
    EngineFactory subclass / an ``engine_factory`` callable)."""
    if "." in class_path:
        module_path, _, attr = class_path.rpartition(".")
        try:
            module = importlib.import_module(module_path)
            return getattr(module, attr)
        except (ImportError, AttributeError):
            pass
    module = importlib.import_module(class_path)
    for name in ("engine_factory", "EngineFactory"):
        if hasattr(module, name):
            return getattr(module, name)
    raise ImportError(f"cannot resolve {class_path!r}")


def resolve_engine_factory(class_path: str):
    obj = resolve_attr(class_path)
    return obj() if isinstance(obj, type) else obj


def load_variant(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def engine_from_variant(variant: dict):
    factory_path = variant.get("engineFactory")
    if not factory_path:
        raise CommandError(
            "engine.json must define 'engineFactory' "
            "(a predictionio_tpu EngineFactory class path)"
        )
    factory = resolve_engine_factory(factory_path)
    return factory.apply(), factory_path


# --- command handlers ---


def cmd_build(args) -> int:
    """Register the engine manifest (reference Console.build:811 +
    RegisterEngine.scala:33-136 — minus the sbt compile, which Python
    doesn't need)."""
    from predictionio_tpu.data.storage import get_storage
    from predictionio_tpu.data.storage.base import EngineManifest

    variant = load_variant(args.variant)
    engine, factory_path = engine_from_variant(variant)  # validates
    manifest = EngineManifest(
        id=variant.get("id", factory_path),
        version=variant.get("version", "0.1.0"),
        name=variant.get("description", factory_path),
        engine_factory=factory_path,
        files=(args.variant,),
    )
    get_storage().get_meta_data_engine_manifests().update(manifest, upsert=True)
    print(f"Registered engine {manifest.id} {manifest.version}")
    return 0


def cmd_train(args) -> int:
    """Reference Console.train:846 -> CreateWorkflow -> CoreWorkflow."""
    from predictionio_tpu.data.storage.base import EngineInstance
    from predictionio_tpu.workflow.core_workflow import CoreWorkflow
    from predictionio_tpu.workflow.workflow_params import WorkflowParams

    if args.coordinator or args.num_hosts or args.host_rank is not None:
        # must run before any other JAX usage; strict — a mis-wired pod
        # aborts rather than silently training single-host
        from predictionio_tpu.parallel import initialize_distributed

        initialize_distributed(
            coordinator_address=args.coordinator,
            num_processes=args.num_hosts,
            process_id=args.host_rank,
        )

    from predictionio_tpu.tools.template import verify_template_min_version
    import os

    if not verify_template_min_version(
        os.path.dirname(os.path.abspath(args.variant))
    ):
        raise CommandError(
            "this engine template requires a newer predictionio_tpu "
            "(template.json pio.version.min)"
        )
    variant = load_variant(args.variant)
    engine, factory_path = engine_from_variant(variant)
    engine_params = engine.jvalue_to_engine_params(variant)
    now = _dt.datetime.now(_dt.timezone.utc)
    instance = EngineInstance(
        id="",
        status="",
        start_time=now,
        end_time=now,
        engine_id=variant.get("id", factory_path),
        engine_version=variant.get("version", "0.1.0"),
        engine_variant=args.variant,
        engine_factory=factory_path,
        batch=args.batch,
    )
    workflow_params = WorkflowParams(
        batch=args.batch,
        verbose=args.verbose,
        skip_sanity_check=args.skip_sanity_check,
        stop_after_read=args.stop_after_read,
        stop_after_prepare=args.stop_after_prepare,
        profile_dir=args.profile_dir,
    )
    if getattr(args, "continuous", False):
        return _train_continuous(
            engine, engine_params, instance, workflow_params, args
        )
    instance_id = CoreWorkflow.run_train(
        engine, engine_params, instance, workflow_params=workflow_params
    )
    if instance_id is None:
        if args.host_rank:  # worker ranks compute; rank 0 records
            print(
                f"Training completed on worker host {args.host_rank} "
                "(instance recorded by host 0)."
            )
        else:
            print("Training interrupted by stop-after flag.")
        return 0
    print(f"Training completed. Engine instance: {instance_id}")
    return 0


def _train_continuous(
    engine, engine_params, instance, workflow_params, args
) -> int:
    """``pio train --continuous``: the poll→delta-fold→warm-train→
    checkpoint loop (workflow/continuous.py). SIGINT/SIGTERM set the
    stop event; the loop ends at the next round boundary."""
    import signal
    import threading

    from predictionio_tpu.workflow.continuous import continuous_train

    stop = threading.Event()

    def _request_stop(signum, frame):
        print("\nStopping after the current round...", flush=True)
        stop.set()

    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, _request_stop)
        except ValueError:  # not the main thread (tests)
            break

    promotion = None
    if getattr(args, "promote_url", None):
        # close the retrain→serve loop: every trained round runs the
        # gated swap pipeline against the named serving fleet
        # (workflow/promotion.py — shadow gate, pinned-id /reload
        # convergence, worker-side drain, post-swap observation with
        # automatic rollback)
        from predictionio_tpu.data.storage import get_storage
        from predictionio_tpu.workflow.promotion import (
            FleetTarget,
            PromotionConfig,
            PromotionPipeline,
        )

        promotion = PromotionPipeline(
            FleetTarget(
                args.promote_url,
                workers_per_url=args.promote_workers_per_url,
            ),
            PromotionConfig(
                observe_s=args.promote_observe_s,
                max_error_rate=args.promote_max_error_rate,
                drain_timeout_s=args.promote_drain_timeout_s,
                require_shadow=bool(args.promote_require_shadow),
                collector_url=(
                    getattr(args, "promote_collector_url", None) or None
                ),
            ),
            storage=get_storage(),
        )
        if not (getattr(args, "shadow_queries", 0) or 0):
            print(
                "note: promotion without --shadow-queries has no quality "
                "gate before the swap (only the post-swap observation "
                "window); pass --shadow-queries N to gate on the shadow "
                "verdict",
                file=sys.stderr,
            )

    def on_round(rep) -> None:
        # structured (trace-correlated) status, not stderr print: a
        # continuous daemon's per-round output is operational telemetry
        # an operator greps/joins by traceId, exactly what the JSON log
        # format exists for (PIO_LOG_FORMAT=json)
        if rep.skipped:
            logger.info(
                "round %d: store unchanged, skipped (%.3fs)",
                rep.round, rep.wall_s,
            )
            return
        logger.info(
            "round %d: instance %s in %.3fs (pack_cache=%s%s%s%s)",
            rep.round, rep.instance_id, rep.wall_s, rep.pack_cache,
            (
                f", resident={rep.resident}"
                if rep.resident is not None
                else ""
            ),
            (
                f", {rep.delta_events} delta events"
                if rep.delta_events is not None
                else ""
            ),
            (
                f", {rep.sweeps} sweeps, final delta "
                f"{rep.final_factor_delta}"
                if rep.sweeps is not None
                else ""
            ),
        )
        if rep.shadow:
            logger.info(
                "round %d shadow: %s vs live %s — %s (jaccard %.4f, "
                "displacement %.2f, %d queries)",
                rep.round, rep.shadow["candidateVersion"],
                rep.shadow["liveVersion"], rep.shadow["verdict"],
                rep.shadow["jaccard_mean"],
                rep.shadow["rank_displacement_mean"],
                rep.shadow["queries"],
            )
        if rep.promotion:
            logger.info(
                "round %d promotion: %s — candidate %s, fleet serving %s"
                "%s",
                rep.round, rep.promotion.get("outcome"),
                rep.promotion.get("candidate"),
                rep.promotion.get("serving"),
                (
                    f" ({rep.promotion.get('reason')})"
                    if rep.promotion.get("reason")
                    else ""
                ),
            )

    print(
        f"Continuous training every {args.interval:g}s "
        "(Ctrl-C / SIGTERM stops)",
        flush=True,
    )
    rounds = continuous_train(
        engine, engine_params, instance,
        workflow_params=workflow_params,
        interval_s=args.interval,
        stop_event=stop,
        max_rounds=args.max_rounds,
        on_round=on_round,
        shadow_queries=getattr(args, "shadow_queries", 0) or 0,
        shadow_min_jaccard=getattr(args, "shadow_min_jaccard", 0.5),
        promotion=promotion,
    )
    print(f"Continuous training stopped after {rounds} round(s).")
    return 0


def cmd_eval(args) -> int:
    """Reference Console eval -> Workflow.runEvaluation."""
    from predictionio_tpu.workflow.core_workflow import CoreWorkflow

    evaluation_cls = resolve_attr(args.evaluation_class)
    evaluation = (
        evaluation_cls() if isinstance(evaluation_cls, type) else evaluation_cls
    )
    if args.engine_params_generator_class:
        epg_cls = resolve_attr(args.engine_params_generator_class)
        epg = epg_cls() if isinstance(epg_cls, type) else epg_cls
        params_list = list(epg.engine_params_list)
    else:
        params_list = getattr(evaluation, "engine_params_list", None)
        if params_list is None:
            raise CommandError(
                f"{args.evaluation_class} defines no engine_params_list; "
                "pass an EngineParamsGenerator class as the second argument"
            )
        params_list = list(params_list)
    from predictionio_tpu.workflow.workflow_params import WorkflowParams

    result = CoreWorkflow.run_evaluation(
        evaluation,
        params_list,
        workflow_params=WorkflowParams(
            grid_train=args.grid_train,
            eval_parallelism=args.eval_parallelism,
        ),
    )
    print(result.to_one_liner())
    return 0


def cmd_deploy(args) -> int:
    """Reference Console.deploy:869 -> CreateServer. With ``--workers N``
    this becomes the serving analog of ``eventserver --workers``: N
    engine-server PROCESSES bind the same port via SO_REUSEPORT (the
    kernel balances accepted connections), each with its OWN prepared
    serving state — resident sharded item factors pinned to its own
    device or mesh slice (``--serving-device``, auto-round-robined over
    the visible devices when not given). One GIL per worker, one device
    slice per worker: the multi-worker saturation shape of the
    retrieval tier (docs/PERF.md)."""
    from predictionio_tpu.api.engine_server import ServerConfig, create_server

    workers = max(1, int(getattr(args, "workers", 1) or 1))
    if workers > 1:
        return _deploy_worker_fleet(args, workers)
    variant = load_variant(args.variant)
    engine, _ = engine_from_variant(variant)
    config = ServerConfig(
        ip=args.ip,
        port=args.port,
        engine_instance_id=args.engine_instance_id,
        feedback=args.feedback,
        event_server_ip=args.event_server_ip,
        event_server_port=args.event_server_port,
        access_key=args.accesskey,
        batch_window_ms=args.batch_window_ms,
        max_batch=args.max_batch,
        pipeline_depth=args.pipeline_depth,
        transport=args.transport,
        reuse_port=bool(getattr(args, "reuse_port", False)),
        serving_devices=getattr(args, "serving_device", None),
        retained_states=int(getattr(args, "retained_states", 1)),
    )
    server = create_server(engine, config)
    _maybe_start_sideband(args, access_key=args.accesskey or "")
    print(f"Engine server serving on {args.ip}:{server.port}")
    server.serve_forever()
    return 0


def _maybe_start_sideband(args, access_key: str = ""):
    """Start the per-process observability sideband when --metrics-port
    was given (api/sideband.py): the individually-scrapable address an
    SO_REUSEPORT worker needs for exact fleet federation."""
    port = int(getattr(args, "metrics_port", 0) or 0)
    if not port:
        return None
    from predictionio_tpu.api.sideband import ObservabilitySideband

    try:
        sideband = ObservabilitySideband(
            ip=args.ip, port=port, access_key=access_key
        ).start()
    except ValueError as e:
        raise CommandError(str(e)) from e
    print(f"Observability sideband on {args.ip}:{sideband.port}")
    return sideband


def _free_port(ip: str) -> int:
    import socket

    host = "127.0.0.1" if ip == "localhost" else ip
    # bind with the ip's OWN address family — AF_INET against "::1"
    # would abort a deploy on the loopback the sideband supports
    family = socket.getaddrinfo(host, None)[0][0]
    with socket.socket(family) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def _deploy_worker_fleet(args, workers: int) -> int:
    """Spawn the SO_REUSEPORT engine-server fleet (the eventserver
    --workers recipe applied to serving): per-worker subprocesses with
    a device assignment each, shared-storage validation, and a
    SUPERVISOR (tools/fleet.py) that restarts crashed workers with
    capped backoff — surfaced as
    ``pio_fleet_worker_restarts_total{worker}`` and in ``pio top`` —
    instead of leaving the fleet silently degraded."""
    import subprocess

    if args.port == 0:
        print(
            "deploy: --workers requires a fixed --port (port 0 would "
            "give every worker its own ephemeral port)",
            file=sys.stderr,
        )
        return 2
    from predictionio_tpu.data.storage import get_storage

    # every worker must see the SAME trained instance + models: a
    # per-process memory store would leave N-1 workers with nothing
    # (or worse, nothing to deploy at all)
    storage = get_storage()
    for repo in ("METADATA", "MODELDATA", "EVENTDATA"):
        if storage.repository_type(repo) == "memory":
            print(
                f"deploy: --workers needs a multi-process-shared {repo} "
                "store (sqlite file, localfs, or http gateway); the "
                "'memory' backend would give each worker a private "
                "store",
                file=sys.stderr,
            )
            return 2

    # device assignment: an explicit --serving-device list is dealt
    # round-robin across workers (each worker gets a disjoint slice);
    # otherwise each worker pins one of the visible devices in turn
    # (no pinning on a single-device host — nothing to partition)
    if getattr(args, "serving_device", None):
        pool = [p for p in str(args.serving_device).split(",") if p.strip()]
    else:
        import jax

        n_dev = len(jax.devices())
        pool = [str(i) for i in range(n_dev)] if n_dev > 1 else []

    def worker_devices(w: int) -> Optional[str]:
        if not pool:
            return None
        mine = pool[w % len(pool) :: workers] if len(pool) >= workers else [
            pool[w % len(pool)]
        ]
        return ",".join(mine)

    # exact fleet federation: with a collector to register with (or an
    # explicit --metrics-port base), every worker gets its OWN sideband
    # observability port — the shared SO_REUSEPORT serving port routes a
    # scrape to an arbitrary worker, so it cannot enumerate the fleet
    collector_url = getattr(args, "collector_url", None)
    sideband_ports: list = []
    if collector_url or getattr(args, "metrics_port", 0):
        base = int(getattr(args, "metrics_port", 0) or 0)
        for w in range(workers):
            sideband_ports.append(base + w if base else _free_port(args.ip))

    def worker_cmd(w: int) -> list:
        cmd = [
            sys.executable, "-m", "predictionio_tpu.tools.cli",
            "deploy", "-v", args.variant,
            "--ip", args.ip, "--port", str(args.port),
            "--workers", "1", "--reuse-port",
            "--transport", args.transport,
            "--batch-window-ms", str(args.batch_window_ms),
            "--max-batch", str(args.max_batch),
            "--pipeline-depth", str(args.pipeline_depth),
            "--event-server-ip", args.event_server_ip,
            "--event-server-port", str(args.event_server_port),
            "--retained-states", str(getattr(args, "retained_states", 1)),
        ]
        if args.engine_instance_id:
            cmd += ["--engine-instance-id", args.engine_instance_id]
        if args.feedback:
            cmd += ["--feedback"]
        if args.accesskey:
            cmd += ["--accesskey", args.accesskey]
        if sideband_ports:
            cmd += ["--metrics-port", str(sideband_ports[w])]
        devs = worker_devices(w)
        if devs is not None:
            cmd += ["--serving-device", devs]
        return cmd

    from predictionio_tpu.api.http import JsonHTTPServer
    from predictionio_tpu.tools.fleet import run_worker_fleet

    def on_started() -> None:
        print(
            f"Engine server: {workers} workers sharing "
            f"{args.ip}:{args.port} (SO_REUSEPORT, one prepared serving "
            "state per worker; crashed workers restart with capped "
            "backoff)"
        )

    rc = run_worker_fleet(
        lambda w: subprocess.Popen(worker_cmd(w)),
        workers,
        fleet_name="deploy",
        grace_s=(
            1.0
            + JsonHTTPServer.BIND_RETRIES * JsonHTTPServer.BIND_RETRY_DELAY_S
        ),
        on_started=on_started,
        collector_url=collector_url,
        worker_urls=[
            f"http://{args.ip}:{p}" for p in sideband_ports
        ] if collector_url else None,
    )
    if rc == 1:
        print(
            "deploy: workers failed to start (see tracebacks above); "
            "aborting",
            file=sys.stderr,
        )
    return rc


def cmd_undeploy(args) -> int:
    """Reference Console.undeploy:934 — HTTP GET /stop."""
    url = f"http://{args.ip}:{args.port}/stop"
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            print(resp.read().decode())
        return 0
    except Exception as e:
        print(f"Undeploy failed: {e}", file=sys.stderr)
        return 1


def cmd_eventserver(args) -> int:
    from predictionio_tpu.api.event_server import (
        EventServerConfig,
        create_event_server,
    )

    workers = max(1, int(getattr(args, "workers", 1) or 1))
    if workers > 1:
        # scale-out past one GIL-bound accept loop: N worker PROCESSES
        # bind the same port with SO_REUSEPORT; the kernel balances
        # accepted connections. The configured storage must be shared
        # across processes (sqlite WAL file or the storage gateway —
        # NOT the in-memory backend, which each worker would own alone).
        import signal
        import subprocess
        import sys
        import time as _time

        if args.port == 0:
            # each worker would kernel-assign a DIFFERENT ephemeral port
            # — no shared accept group, no single advertised address
            print(
                "eventserver: --workers requires a fixed --port "
                "(port 0 would give every worker its own ephemeral port)",
                file=sys.stderr,
            )
            return 2
        from predictionio_tpu.data.storage import get_storage

        # a per-process store would silently break the fleet: memory
        # EVENTDATA scatters events across N private universes (every
        # POST 201s, training sees ~1/N); memory METADATA gives every
        # worker an empty access-key table (all POSTs 401)
        storage = get_storage()
        for repo in ("EVENTDATA", "METADATA"):
            if storage.repository_type(repo) == "memory":
                print(
                    f"eventserver: --workers needs a multi-process-shared "
                    f"{repo} store (sqlite file or http gateway); the "
                    "'memory' backend would give each worker a private "
                    "store",
                    file=sys.stderr,
                )
                return 2
        cmd = [
            sys.executable, "-m", "predictionio_tpu.tools.cli",
            "eventserver", "--ip", args.ip, "--port", str(args.port),
            "--workers", "1", "--reuse-port",
            "--transport", args.transport,
        ]
        if args.stats:
            cmd.append("--stats")
        # exactly ONE worker runs the segment compactor (concurrent
        # compactors are safe — the manifest commit re-validates the
        # watermark — but N of them would duplicate the sealing work)
        procs = [
            subprocess.Popen(
                cmd
                + (
                    ["--no-compact"]
                    if (w > 0 or getattr(args, "no_compact", False))
                    else []
                )
                # per-worker sideband ports (base + slot): each worker
                # individually scrapable for exact fleet federation
                + (
                    ["--metrics-port", str(args.metrics_port + w)]
                    + (
                        [
                            "--metrics-access-key",
                            args.metrics_access_key,
                        ]
                        if getattr(args, "metrics_access_key", "")
                        else []
                    )
                    if getattr(args, "metrics_port", 0)
                    else []
                )
            )
            for w in range(workers)
        ]

        shutdown = {"requested": False}

        def forward(signum, frame):
            shutdown["requested"] = True
            for p in procs:
                p.terminate()

        signal.signal(signal.SIGTERM, forward)
        signal.signal(signal.SIGINT, forward)
        # grace check: a worker that failed to bind (port held by a
        # non-reuse listener, missing SO_REUSEPORT) dies within its bind
        # retries — report a partial fleet instead of printing success
        # over it
        from predictionio_tpu.api.http import JsonHTTPServer

        _time.sleep(
            1.0
            + JsonHTTPServer.BIND_RETRIES * JsonHTTPServer.BIND_RETRY_DELAY_S
        )
        dead = [p for p in procs if p.poll() is not None]
        if dead:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            for p in procs:
                p.wait()
            print(
                f"eventserver: {len(dead)}/{workers} workers failed to "
                "start (see tracebacks above); aborting",
                file=sys.stderr,
            )
            return 1
        print(
            f"Event server: {workers} workers sharing {args.ip}:{args.port} "
            "(SO_REUSEPORT)"
        )
        rc = 0
        for p in procs:
            code = p.wait()
            if shutdown["requested"] and code < 0:
                # worker killed by the signal we forwarded: a clean
                # operator Ctrl-C / SIGTERM stop is success, not the
                # worker's -SIGTERM returncode bubbling up as failure
                code = 0
            rc = code or rc
        return rc

    server = create_event_server(
        EventServerConfig(
            ip=args.ip, port=args.port, stats=args.stats,
            reuse_port=bool(getattr(args, "reuse_port", False)),
            transport=args.transport,
            compact=not getattr(args, "no_compact", False),
        )
    )
    _maybe_start_sideband(
        args, access_key=getattr(args, "metrics_access_key", "") or ""
    )
    print(f"Event server serving on {args.ip}:{server.port}")
    server.serve_forever()
    return 0


def cmd_compact(args) -> int:
    """Standalone segment compaction (the event server runs the same
    daemon in-process by default): one round per app, or a daemon loop
    with --interval."""
    from predictionio_tpu.data.storage import get_storage
    from predictionio_tpu.data.store import app_name_to_id
    from predictionio_tpu.data.storage.segments import (
        CompactionPolicy,
        SegmentCompactor,
    )

    storage = get_storage()
    if not SegmentCompactor.supported(storage):
        print(
            "compact: the configured EVENTDATA backend has no segment "
            "tier (sqlite only); nothing to do",
            file=sys.stderr,
        )
        return 2
    policy = CompactionPolicy(
        cold_s=args.cold_s, min_events=args.min_events, grace_s=args.grace_s
    )
    apps = None
    if args.app:
        app_id, _ = app_name_to_id(args.app, None, storage)
        apps = [app_id]
    compactor = SegmentCompactor(
        storage, policy=policy,
        interval_s=args.interval or 60.0, apps=apps,
    )

    def run_round() -> None:
        if args.app and args.channel:
            app_id, channel_id = app_name_to_id(
                args.app, args.channel, storage
            )
            results = {app_id: compactor.run_once(app_id, channel_id)}
        else:
            results = compactor.compact_all_once()
        for app_id, r in results.items():
            # structured status (not stderr print): daemon rounds are
            # operational telemetry, joinable against traces/metrics
            logger.info("compact app %d: %s", app_id, r)

    run_round()
    if args.interval > 0:
        import signal
        import threading

        stop = threading.Event()

        def _request_stop(signum, frame):
            stop.set()

        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                signal.signal(sig, _request_stop)
            except ValueError:  # not the main thread
                break
        print(
            f"compact: daemon mode, every {args.interval:g}s "
            "(Ctrl-C / SIGTERM stops)"
        )
        # shutdown-aware poll loop (the while-True lint's sanctioned
        # shape): park on the event, run a round, re-check
        while not stop.is_set():
            if stop.wait(args.interval):
                break
            run_round()
    return 0


def cmd_storagegateway(args) -> int:
    from predictionio_tpu.api.storage_gateway import (
        _LOOPBACK_IPS,
        StorageGatewayServer,
    )

    if not args.secret and args.ip not in _LOOPBACK_IPS:
        print(
            "WARNING: binding a non-loopback interface without --secret "
            "exposes unauthenticated read/write access to ALL storage"
        )
    server = StorageGatewayServer(
        ip=args.ip, port=args.port, secret=args.secret,
        allow_insecure=True,  # the explicit --ip flag + warning above
        transport=args.transport,
    )
    print(f"Storage gateway serving on {args.ip}:{server.port}")
    server.serve_forever()
    return 0


def _cluster_client(source: str = ""):
    """The cluster StorageClient behind EVENTDATA (or an explicit
    ``--source``); errors out when no cluster source is configured."""
    from predictionio_tpu.data.storage import get_storage
    from predictionio_tpu.data.storage import cluster as cluster_mod

    storage = get_storage()
    names = []
    if source:
        names = [source.upper()]
    else:
        repos = storage.repositories()
        ev = repos.get("EVENTDATA", {}).get("SOURCE")
        if ev:
            names = [ev]
    for name in names:
        try:
            client = storage._client(name)
        except Exception:
            continue
        if isinstance(client, cluster_mod.StorageClient):
            return client
    raise SystemExit(
        "no cluster storage source configured "
        "(PIO_STORAGE_SOURCES_<NAME>_TYPE=cluster); see docs/STORAGE.md"
    )


def cmd_storagecluster(args) -> int:
    """Operate the partitioned gateway tier: ``status`` renders the
    per-node topology/health table, ``resync`` replays missed rows onto
    recovered stale nodes (docs/STORAGE.md runbook)."""
    client = _cluster_client(getattr(args, "source", ""))
    if args.cluster_command == "resync":
        report = client.resync(full=args.full)
        for label, outcome in sorted(report["nodes"].items()):
            print(f"  {label}: {outcome}")
        print(f"resynced events: {report['events']}")
        return 0 if "failed" not in str(report) else 1
    # status (default)
    print(
        f"cluster: {client.n_nodes} nodes, R={client.replicas}, "
        f"write quorum={client.write_quorum}"
    )
    print(
        f"{'NODE':<28} {'SLOT':>4} {'REPLICA-OF':<12} {'STATE':<8} "
        f"{'STALE':<6} {'AGE':>8} {'LAG':>8}"
    )
    for row in client.status():
        state = (
            "down" if not row["available"]
            else ("open" if row["breaker_open"] else "ok")
        )
        # AGE = wall seconds out of the read path; LAG = the event-time
        # gap to the resync source measured at the last resync attempt
        age = f"{row['stale_age_s']:.0f}s" if row["stale"] else "-"
        lag = (
            f"{row['resync_lag_s']:.0f}s"
            if row["stale"] and row["resync_lag_s"]
            else "-"
        )
        print(
            f"{row['url']:<28} {row['primary_slot']:>4} "
            f"{','.join(map(str, row['replica_slots'])):<12} "
            f"{state:<8} {'yes' if row['stale'] else 'no':<6} "
            f"{age:>8} {lag:>8}"
        )
    return 0


def cmd_trace(args) -> int:
    """Fetch a span dump and print it as an indented span tree (see
    docs/OBSERVABILITY.md for the span model). ``--url`` reads ONE
    server's /debug/traces.json ring; ``--collector`` reads a telemetry
    collector's /api/traces.json — the fleet's spans STITCHED across
    processes by trace id, each annotated with the process it was
    pulled from."""
    import json as _json
    import urllib.parse as _up
    import urllib.request as _ur

    from predictionio_tpu.utils.tracing import format_trace

    params = {}
    if args.trace_id:
        params["traceId"] = args.trace_id
    collector = getattr(args, "collector", None)
    if collector:
        url = collector.rstrip("/") + "/api/traces.json"
    else:
        if args.access_key:
            params["accessKey"] = args.access_key
        if args.secret:
            params["secret"] = args.secret
        url = args.url.rstrip("/") + "/debug/traces.json"
    if params:
        url += "?" + _up.urlencode(params)
    try:
        with _ur.urlopen(url, timeout=10) as resp:
            payload = _json.loads(resp.read().decode("utf-8"))
    except Exception as e:
        print(f"trace: fetching {url} failed: {e}", file=sys.stderr)
        return 1
    spans = payload.get("spans", [])
    if not spans:
        print("trace: no spans recorded")
        return 0
    if args.json:
        print(_json.dumps(spans, indent=2))
        return 0
    if collector:
        # a stitched tree spans processes: show each span's origin
        spans = [
            {
                **s,
                "name": (
                    f"{s['name']} [{s['instance']}]"
                    if s.get("instance")
                    else s["name"]
                ),
            }
            for s in spans
        ]
    # group by trace so unrelated requests don't interleave
    by_trace: dict = {}
    for s in spans:
        by_trace.setdefault(s["traceId"], []).append(s)
    for trace_id, group in by_trace.items():
        print(f"trace {trace_id} ({len(group)} span(s)):")
        tree = format_trace(group)
        print("\n".join("  " + line for line in tree.splitlines()))
    return 0


def cmd_profile(args) -> int:
    """``pio profile``: drive one bounded on-demand jax.profiler capture
    on a running server's gated ``POST /debug/profile`` endpoint
    (``--collector`` relays through a telemetry collector's
    ``POST /api/profile`` instead) and write the returned trace archive
    to a zip — TensorBoard's profile plugin or Perfetto loads it."""
    import base64 as _b64
    import json as _json
    import urllib.parse as _up
    import urllib.request as _ur

    collector = getattr(args, "collector", None)
    timeout = float(args.seconds) + 60.0
    try:
        if collector:
            body = {"target": args.url, "seconds": args.seconds}
            if args.secret:
                body["secret"] = args.secret
            req = _ur.Request(
                collector.rstrip("/") + "/api/profile",
                data=_json.dumps(body).encode("utf-8"),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
        else:
            params = {"seconds": str(args.seconds)}
            if args.access_key:
                params["accessKey"] = args.access_key
            if args.secret:
                params["secret"] = args.secret
            req = _ur.Request(
                args.url.rstrip("/")
                + "/debug/profile?"
                + _up.urlencode(params),
                data=b"",
                method="POST",
            )
        with _ur.urlopen(req, timeout=timeout) as resp:
            payload = _json.loads(resp.read().decode("utf-8"))
    except Exception as e:
        print(f"profile: capture failed: {e}", file=sys.stderr)
        return 1
    archive = payload.get("archive_b64")
    if not archive:
        print(f"profile: no archive in response: {payload}", file=sys.stderr)
        return 1
    data = _b64.b64decode(archive)
    with open(args.out, "wb") as f:
        f.write(data)
    print(
        f"profile: wrote {len(data)} bytes "
        f"({len(payload.get('files', []))} trace files, "
        f"{payload.get('seconds')}s capture) to {args.out}"
    )
    return 0


def cmd_collector(args) -> int:
    """``pio collector``: the fleet telemetry collector daemon
    (tools/collector.py + utils/telemetry.py) — federated /metrics,
    /api/fleet.json, cross-process /api/traces.json, and the SLO
    burn-rate /api/alerts.json over the registered targets."""
    from predictionio_tpu.tools.collector import CollectorServer
    from predictionio_tpu.utils.telemetry import Collector, load_slos

    targets = list(args.targets or [])
    if args.targets_file:
        try:
            with open(args.targets_file, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if line and not line.startswith("#"):
                        targets.append(line)
        except OSError as e:
            raise CommandError(f"collector: {e}") from e
    slos = None
    if args.slo_file:
        try:
            slos = load_slos(args.slo_file)
        except (OSError, ValueError) as e:
            raise CommandError(f"collector: bad --slo-file: {e}") from e
    try:
        collector = Collector(
            targets,
            poll_interval_s=args.interval,
            retention=args.retention,
            slos=slos,
            access_key=args.access_key or "",
            secret=args.secret or "",
        )
        server = CollectorServer(
            collector,
            ip=args.ip,
            port=args.port,
            admin_secret=args.admin_secret or "",
            transport=args.transport,
        )
    except ValueError as e:
        raise CommandError(f"collector: {e}") from e
    collector.start()
    server.start()
    print(
        f"Telemetry collector serving on {args.ip}:{server.port} "
        f"({len(collector.target_urls())} target(s), "
        f"poll every {args.interval:g}s, "
        f"{len(collector.slos)} SLO(s))"
    )
    try:
        server.serve_forever()
    finally:
        collector.stop()
    return 0


def _experiment_http(url: str, payload=None, timeout: float = 30.0):
    """One JSON round-trip for the experiment surfaces; HTTP errors
    surface the server's message as a CommandError."""
    import urllib.error
    import urllib.request

    data = None
    headers = {}
    if payload is not None:
        data = json.dumps(payload).encode("utf-8")
        headers["Content-Type"] = "application/json"
    req = urllib.request.Request(
        url, data=data, headers=headers,
        method="POST" if payload is not None else "GET",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        try:
            detail = json.loads(e.read().decode("utf-8")).get("message")
        except Exception:
            detail = str(e)
        raise CommandError(f"experiment: {detail}") from e
    except OSError as e:
        raise CommandError(f"experiment: {url}: {e}") from e


def _experiment_converge(server_url, payload, done, workers, timeout_s=60.0):
    """Converge an SO_REUSEPORT fleet on an experiment control action.

    A POST to a shared serving port reaches ONE arbitrary worker, so —
    exactly like the promotion tier's ``FleetTarget`` — keep re-POSTing
    the idempotent request (each round-trip is a fresh connection the
    kernel balances onto some worker) and require ``max(3, 2*workers)``
    consecutive GETs to satisfy ``done`` before declaring the fleet
    converged. Returns every non-trivial POST report, first first."""
    import time

    confirms = max(3, 2 * max(1, int(workers)))
    deadline = time.monotonic() + timeout_s
    streak = 0
    reports = []
    while time.monotonic() < deadline:
        reports.append(_experiment_http(server_url, payload))
        if done(_experiment_http(server_url)):
            streak += 1
            if streak >= confirms:
                return reports
        else:
            streak = 0
        time.sleep(0.1)
    raise CommandError(
        f"experiment: fleet did not converge within {timeout_s:g}s "
        f"(workers={workers}; is every worker serving an arm's "
        f"instance?)"
    )


def cmd_experiment(args) -> int:
    """``pio experiment start|status|stop``: drive the online
    experimentation plane (workflow/experiment.py) on a running engine
    server — and, with ``--collector``, register the experiment for
    fleet-wide sequential evaluation on the telemetry collector."""
    import urllib.parse

    base = args.url.rstrip("/")
    qs = (
        "?" + urllib.parse.urlencode({"accessKey": args.access_key})
        if args.access_key
        else ""
    )
    server_url = base + "/experiment.json" + qs
    collector = (args.collector or "").rstrip("/")

    if args.experiment_command == "status":
        status = _experiment_http(server_url)
        print(json.dumps(status, indent=2))
        if collector:
            reports = _experiment_http(
                collector + "/api/experiments.json"
            )
            print(json.dumps(reports, indent=2))
        return 0

    if args.experiment_command == "stop":
        payload = {"stop": True}
        if args.winner:
            payload["winner"] = args.winner
        # converge: a worker that already stopped answers
        # {"stopped": false} — harmless; done when consecutive reads
        # all report no active experiment
        reports = _experiment_converge(
            server_url, payload,
            done=lambda s: s.get("experiment") is None,
            workers=args.workers,
        )
        stopped = [r for r in reports if r.get("stopped")]
        report = stopped[0] if stopped else reports[0]
        for extra in stopped[1:]:  # other workers' drain/retain sets
            for k in ("drained", "retained"):
                report[k] = sorted(set(report.get(k, [])) | set(extra.get(k, [])))
        print(json.dumps(report, indent=2))
        if collector and report.get("experiment"):
            _experiment_http(
                collector + "/api/experiments.json",
                {"remove": report["experiment"], "secret": args.secret},
            )
            print(f"removed from collector: {report['experiment']}")
        return 0

    # start
    if args.spec:
        try:
            with open(args.spec, "r", encoding="utf-8") as f:
                spec = json.load(f)
        except (OSError, ValueError) as e:
            raise CommandError(f"experiment: bad --spec: {e}") from e
    else:
        if not args.name or len(args.variant_id or []) < 2:
            raise CommandError(
                "experiment start needs --spec, or --name plus at "
                "least two --variant-id"
            )
        spec = {"name": args.name, "variants": list(args.variant_id)}
        if args.split:
            try:
                spec["split"] = [
                    float(s) for s in args.split.split(",") if s
                ]
            except ValueError as e:
                raise CommandError(
                    f"experiment: bad --split: {e}"
                ) from e
        if args.salt:
            spec["salt"] = args.salt
        if args.user_field:
            spec["user_field"] = args.user_field
        spec["horizon_s"] = args.horizon_s
        spec["alpha"] = args.alpha
        spec["on_inconclusive"] = args.on_inconclusive
    exp_name = str(spec.get("name", ""))
    _experiment_converge(
        server_url, {"spec": spec},
        done=lambda s: (s.get("experiment") or {}).get("spec", {})
        .get("name") == exp_name,
        workers=args.workers,
    )
    status = _experiment_http(server_url)
    print(json.dumps(status, indent=2))
    if collector:
        out = _experiment_http(
            collector + "/api/experiments.json",
            {"spec": spec, "secret": args.secret},
        )
        print(f"registered on collector: {json.dumps(out)}")
    return 0


def cmd_replay(args) -> int:
    """``pio replay``: re-run a prediction capture (a saved
    ``/debug/predictions.json`` dump or a JSON-lines capture file)
    against a persisted model instance and report divergence — the
    deterministic regression oracle for model swaps. A self-replay
    against the instance that produced the capture reports exactly
    zero divergence (jaccard 1.0, rank displacement 0)."""
    from predictionio_tpu.api.engine_server import DeployedEngine
    from predictionio_tpu.data.storage import get_storage
    from predictionio_tpu.workflow.quality import (
        load_capture,
        replay_capture,
    )

    records = load_capture(args.capture)
    if args.version:
        records = [r for r in records if r.get("version") == args.version]
    if args.serving_variant:
        # per-arm replay: keep only records served by that experiment
        # arm (records carry "variant" when captured under a running
        # experiment) — self-replay divergence checked per arm
        records = [
            r for r in records
            if r.get("variant") == args.serving_variant
        ]
    if args.num:
        records = records[-args.num:]
    if not records:
        print("replay: capture holds no matching records", file=sys.stderr)
        return 1
    variant = load_variant(args.variant)
    engine, _ = engine_from_variant(variant)
    deployed = DeployedEngine.from_storage(
        engine, get_storage(), engine_instance_id=args.engine_instance_id
    )
    report = replay_capture(records, deployed, batch=args.batch)
    captured_versions = sorted(
        {r.get("version", "unknown") for r in records}
    )
    print(
        f"replayed {report['queries']} queries "
        f"(captured from {', '.join(captured_versions)}) against "
        f"{report['targetVersion']}"
    )
    print(
        f"  jaccard mean {report['jaccard_mean']:.6f} "
        f"min {report['jaccard_min']:.6f}"
    )
    print(
        f"  rank displacement mean {report['rank_displacement_mean']:.4f} "
        f"max {report['rank_displacement_max']:.4f}"
    )
    print(f"  score delta mean {report['score_delta_mean']:.3e}")
    print(f"  diverged: {report['diverged']}/{report['queries']}")
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2)
        print(f"  report written to {args.json}")
    if args.fail_on_divergence and report["diverged"]:
        return 1
    return 0


def cmd_top(args) -> int:
    """Live fleet console (tools/top.py): one row per server URL,
    refreshed every --interval seconds; --once prints a single frame
    (scripting/tests). With ``--collector URL`` the whole fleet renders
    from ONE endpoint — the collector's /api/fleet.json — instead of
    per-server scrapes."""
    import signal
    import threading

    from predictionio_tpu.tools.top import run_top

    collector = getattr(args, "collector", None)
    if not collector and not args.url:
        print(
            "top: pass --url (repeatable) or --collector", file=sys.stderr
        )
        return 2
    stop = threading.Event()

    def _request_stop(signum, frame):
        stop.set()

    if not args.once:
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                signal.signal(sig, _request_stop)
            except ValueError:  # not the main thread (tests)
                break
    return run_top(
        args.url or [],
        interval_s=args.interval,
        iterations=1 if args.once else None,
        stop_event=stop,
        collector=collector,
    )


def cmd_adminserver(args) -> int:
    from predictionio_tpu.tools.admin_server import create_admin_server

    server = create_admin_server(ip=args.ip, port=args.port)
    print(f"Admin server serving on {args.ip}:{server.port}")
    server.serve_forever()
    return 0


def cmd_dashboard(args) -> int:
    from predictionio_tpu.tools.dashboard import create_dashboard

    server = create_dashboard(ip=args.ip, port=args.port)
    print(f"Dashboard serving on {args.ip}:{server.port}")
    server.serve_forever()
    return 0


def cmd_template(args) -> int:
    """Reference Console template get|list (Template.scala:226-415);
    packaged engine templates by name, or ``user/repo`` fetched from
    the GitHub gallery."""
    from predictionio_tpu.tools.template import (
        template_get,
        template_get_remote,
        template_list,
    )

    if args.template_command == "list":
        for t in template_list():
            print(f"{t.name}: {t.description}")
        return 0
    import tarfile

    directory = args.directory or args.name.rsplit("/", 1)[-1]
    try:
        if "/" in args.name:
            template_get_remote(
                args.name, directory, app_name=args.app_name,
                ref=args.ref, sha256=args.sha256,
            )
        else:
            template_get(args.name, directory, app_name=args.app_name)
    except (
        KeyError, FileExistsError, ValueError, OSError,
        tarfile.TarError,  # corrupt/non-tar archive from the gallery
    ) as e:
        raise CommandError(str(e)) from e
    print(f"Engine template {args.name} created at {directory}/")
    return 0


def cmd_run(args) -> int:
    """Run an arbitrary ``fn(ctx)`` under the workflow env (reference
    Console.run:1033 + FakeWorkflow)."""
    from predictionio_tpu.workflow.fake_workflow import run_fake

    func = resolve_attr(args.main)
    result = run_fake(func)
    print(result.to_one_liner())
    return 0


def cmd_shell(args) -> int:
    """Interactive Python with the pio environment loaded (reference
    bin/pio-shell — a Spark shell with the pio classpath)."""
    import code

    from predictionio_tpu.data.storage import get_storage
    from predictionio_tpu.data.store import LEventStore, PEventStore
    from predictionio_tpu.workflow.context import WorkflowContext

    storage = get_storage()
    ctx = WorkflowContext(mode="shell", storage=storage)
    banner = (
        f"predictionio_tpu {__version__} shell\n"
        "bindings: storage, ctx, PEventStore, LEventStore"
    )
    code.interact(
        banner=banner,
        local={
            "storage": storage,
            "ctx": ctx,
            "PEventStore": PEventStore,
            "LEventStore": LEventStore,
        },
    )
    return 0


def cmd_export(args) -> int:
    from predictionio_tpu.tools.export_import import events_to_file

    n = events_to_file(
        args.app_name, args.output, args.channel, format=args.format
    )
    print(f"Exported {n} events to {args.output} ({args.format})")
    return 0


def cmd_import(args) -> int:
    from predictionio_tpu.tools.export_import import file_to_events

    n = file_to_events(args.app_name, args.input, args.channel)
    print(f"Imported {n} events")
    return 0


def cmd_status(args) -> int:
    """Reference Console.status:1066 — storage config + smoke test."""
    from predictionio_tpu.data.storage import get_storage

    storage = get_storage()
    print(f"PredictionIO-TPU {__version__}")
    print("Storage repositories:")
    for repo, conf in sorted(storage.repositories().items()):
        print(f"  {repo}: source={conf.get('SOURCE')} name={conf.get('NAME')}")
    print("Storage sources:")
    for source, conf in sorted(storage.sources().items()):
        print(f"  {source}: type={conf.get('TYPE')}")
    try:
        import jax

        print(f"JAX devices: {jax.devices()}")
        from predictionio_tpu.utils.compilation_cache import (
            ensure_compilation_cache,
        )

        cache_dir = ensure_compilation_cache()
        print(
            f"XLA compilation cache: {cache_dir or 'disabled'}"
        )
    except Exception as e:  # status must not hard-fail on device probing
        print(f"JAX devices unavailable: {e}")
    if storage.verify_all_data_objects():
        print("Storage verification OK. Your system is all ready to go.")
        return 0
    print("Storage verification FAILED.", file=sys.stderr)
    return 1


def _app_description_lines(d) -> List[str]:
    out = [
        f"  App Name: {d.app.name}",
        f"    App ID: {d.app.id}",
        f"    Description: {d.app.description or ''}",
    ]
    for k in d.access_keys:
        allowed = ",".join(k.events) if k.events else "(all)"
        out.append(f"    Access Key: {k.key} | {allowed}")
    for c in d.channels:
        out.append(f"    Channel: {c.name} (id {c.id})")
    return out


def cmd_app(args) -> int:
    client = CommandClient()
    if args.app_command == "new":
        d = client.app_new(
            args.name,
            app_id=args.id or 0,
            description=args.description,
            access_key=args.access_key or "",
        )
        print("App created:")
    elif args.app_command == "list":
        for d in client.app_list():
            print("\n".join(_app_description_lines(d)))
        return 0
    elif args.app_command == "show":
        d = client.app_show(args.name)
    elif args.app_command == "delete":
        client.app_delete(args.name)
        print(f"App {args.name} deleted.")
        return 0
    elif args.app_command == "data-delete":
        client.app_data_delete(
            args.name, channel=args.channel, all_channels=args.all
        )
        print(f"Data of app {args.name} deleted.")
        return 0
    elif args.app_command == "channel-new":
        c = client.channel_new(args.name, args.channel)
        print(f"Channel {c.name} created (id {c.id}).")
        return 0
    elif args.app_command == "channel-delete":
        client.channel_delete(args.name, args.channel)
        print(f"Channel {args.channel} deleted.")
        return 0
    else:
        raise CommandError(f"unknown app command {args.app_command!r}")
    print("\n".join(_app_description_lines(d)))
    return 0


def cmd_accesskey(args) -> int:
    client = CommandClient()
    if args.ak_command == "new":
        k = client.access_key_new(
            args.app_name, key=args.key or "", events=tuple(args.event or ())
        )
        print(f"Created new access key: {k.key}")
    elif args.ak_command == "list":
        for k in client.access_key_list(args.app_name):
            allowed = ",".join(k.events) if k.events else "(all)"
            print(f"{k.key} | app {k.appid} | {allowed}")
    elif args.ak_command == "delete":
        client.access_key_delete(args.key)
        print(f"Deleted access key {args.key}.")
    return 0


def cmd_version(args) -> int:
    print(__version__)
    return 0


def cmd_upgrade(args) -> int:
    """Reference Console.upgrade (Console.scala:1130) — best-effort
    newer-release check; never fails the CLI when offline."""
    from predictionio_tpu.tools.upgrade import check_for_upgrade

    print(check_for_upgrade(url=args.url))
    return 0


# --- parser ---


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pio", description="PredictionIO-TPU console"
    )
    p.add_argument("--verbose", action="store_true")
    sub = p.add_subparsers(dest="command", required=True)

    # app
    app = sub.add_parser("app", help="manage apps")
    app_sub = app.add_subparsers(dest="app_command", required=True)
    ap_new = app_sub.add_parser("new")
    ap_new.add_argument("name")
    ap_new.add_argument("--id", type=int)
    ap_new.add_argument("--description")
    ap_new.add_argument("--access-key")
    app_sub.add_parser("list")
    for name in ("show", "delete"):
        sp = app_sub.add_parser(name)
        sp.add_argument("name")
    dd = app_sub.add_parser("data-delete")
    dd.add_argument("name")
    dd.add_argument("--channel")
    dd.add_argument("--all", action="store_true")
    for name in ("channel-new", "channel-delete"):
        sp = app_sub.add_parser(name)
        sp.add_argument("name")
        sp.add_argument("channel")
    app.set_defaults(func=cmd_app)

    # accesskey
    ak = sub.add_parser("accesskey", help="manage access keys")
    ak_sub = ak.add_subparsers(dest="ak_command", required=True)
    ak_new = ak_sub.add_parser("new")
    ak_new.add_argument("app_name")
    ak_new.add_argument("--key")
    ak_new.add_argument("--event", action="append")
    ak_list = ak_sub.add_parser("list")
    ak_list.add_argument("app_name", nargs="?")
    ak_del = ak_sub.add_parser("delete")
    ak_del.add_argument("key")
    ak.set_defaults(func=cmd_accesskey)

    # build / train / eval / deploy / undeploy
    build = sub.add_parser("build", help="register the engine manifest")
    build.add_argument("-v", "--variant", default="engine.json")
    build.set_defaults(func=cmd_build)

    train = sub.add_parser("train", help="run the training workflow")
    train.add_argument("-v", "--variant", default="engine.json")
    train.add_argument("-b", "--batch", default="")
    train.add_argument("--skip-sanity-check", action="store_true")
    train.add_argument("--stop-after-read", action="store_true")
    train.add_argument("--stop-after-prepare", action="store_true")
    train.add_argument(
        "--profile-dir",
        help="write a jax.profiler trace of the device loop to this "
        "directory (same capture machinery and trace layout as the "
        "servers' POST /debug/profile / `pio profile`)",
    )
    # multi-host training over DCN: run the same command on every host
    # with its own --host-rank (the spark-submit --num-executors analog)
    train.add_argument(
        "--coordinator", help="host:port of host 0 for multi-host training"
    )
    train.add_argument("--num-hosts", type=int)
    train.add_argument("--host-rank", type=int)
    # continuous (delta) training: poll → delta-fold → warm-train →
    # checkpoint until SIGINT/SIGTERM (workflow/continuous.py)
    train.add_argument(
        "--continuous", action="store_true",
        help="retrain in a loop; unchanged stores skip, grown stores "
        "fold only the delta and warm-start from the previous model",
    )
    train.add_argument(
        "--interval", type=float, default=10.0,
        help="seconds between continuous rounds (default 10)",
    )
    train.add_argument(
        "--max-rounds", type=int, default=None,
        help="stop the continuous loop after N rounds (default: run "
        "until signalled)",
    )
    train.add_argument(
        "--shadow-queries", type=int, default=0,
        help="with --continuous: shadow-score each trained round "
        "against the previous instance on the newest N captured "
        "queries (0 disables; see workflow/quality.py)",
    )
    train.add_argument(
        "--shadow-min-jaccard", type=float, default=0.5,
        help="mean-jaccard floor below which a shadow-scored round's "
        "verdict is 'diverged' (default 0.5)",
    )
    # zero-downtime promotion (workflow/promotion.py): with --continuous,
    # every trained round runs the gated swap pipeline against the named
    # serving fleet — shadow-verdict gate, per-worker /reload pinned to
    # the candidate engine-instance id, worker-side drain, post-swap
    # observation window with automatic rollback
    train.add_argument(
        "--promote-url", action="append",
        help="with --continuous: serving-fleet base URL to promote each "
        "trained round to (repeatable: one per worker port; an "
        "SO_REUSEPORT fleet sharing one port passes it once plus "
        "--promote-workers-per-url)",
    )
    train.add_argument(
        "--promote-workers-per-url", type=int, default=1,
        help="workers behind each --promote-url (drives how many "
        "consecutive matching status polls count as fleet convergence)",
    )
    train.add_argument(
        "--promote-observe-s", type=float, default=10.0,
        help="post-swap observation window before a promotion is final; "
        "regressions inside it roll back to the retained previous "
        "instance (0 disables observation+rollback)",
    )
    train.add_argument(
        "--promote-max-error-rate", type=float, default=0.05,
        help="rollback when window 5xx / candidate requests exceeds "
        "this (default 0.05)",
    )
    train.add_argument(
        "--promote-drain-timeout-s", type=float, default=30.0,
        help="bounded drain of the displaced instance (default 30)",
    )
    train.add_argument(
        "--promote-require-shadow", action="store_true",
        help="refuse to promote rounds that produced no shadow sample "
        "(default: promote — fresh deploys have no capture yet)",
    )
    train.add_argument(
        "--promote-collector-url",
        help="telemetry collector base URL (pio collector): the "
        "post-swap observation window reads the FLEET-wide federated "
        "/metrics from it — error rate and hit rate across every "
        "worker and the event server — instead of one process's "
        "counters; size --promote-observe-s to at least two collector "
        "poll intervals",
    )
    train.set_defaults(func=cmd_train)

    ev = sub.add_parser("eval", help="run an evaluation")
    ev.add_argument("evaluation_class")
    ev.add_argument("engine_params_generator_class", nargs="?")
    ev.add_argument(
        "--grid-train", choices=("auto", "always", "never"), default="auto",
        help="device-side batched training of reg-axis grid variants",
    )
    ev.add_argument(
        "--eval-parallelism", type=int, default=4,
        help="concurrent grid variants (the reference's .par)",
    )
    ev.set_defaults(func=cmd_eval)

    deploy = sub.add_parser("deploy", help="start the engine query server")
    deploy.add_argument("-v", "--variant", default="engine.json")
    deploy.add_argument("--ip", default="localhost")
    deploy.add_argument("--port", type=int, default=8000)
    deploy.add_argument("--engine-instance-id")
    deploy.add_argument("--feedback", action="store_true")
    deploy.add_argument("--event-server-ip", default="localhost")
    deploy.add_argument("--event-server-port", type=int, default=7070)
    deploy.add_argument("--accesskey")
    deploy.add_argument(
        "--batch-window-ms", type=float, default=2.0,
        help="micro-batching window for concurrent queries",
    )
    deploy.add_argument(
        "--max-batch", type=int, default=128,
        help="max queries per device batch",
    )
    deploy.add_argument(
        "--pipeline-depth", type=int, default=1,
        help="batches in flight at once (default 1 = strictly serial "
        "serving, matching the reference contract; 2 double-buffers "
        "device dispatch against result fetch — safe only for engines "
        "with no mutable predict-time state, like the packaged "
        "templates; see ServerConfig.pipeline_depth)",
    )
    deploy.add_argument(
        "--transport", choices=("async", "threaded"), default="async",
        help="REST frontend: 'async' = single-threaded event loop with "
        "future-based micro-batch handoff (in-flight queries are queue "
        "entries, thousands of connections cost no OS threads); "
        "'threaded' = stdlib thread-per-connection fallback",
    )
    deploy.add_argument(
        "--workers", type=int, default=1,
        help="engine-server worker processes sharing the port via "
        "SO_REUSEPORT, each with its own prepared serving state pinned "
        "to its own device/mesh slice (requires multi-process-shared "
        "storage: sqlite file, localfs models, or gateway)",
    )
    deploy.add_argument(
        "--reuse-port", action="store_true",
        help="bind with SO_REUSEPORT (set automatically for workers)",
    )
    deploy.add_argument(
        "--retained-states", type=int, default=1,
        help="displaced serving states each worker keeps prepared "
        "(warm, factors resident) after a /reload swap — the promotion "
        "pipeline's instant-rollback store; evicted states drain and "
        "free their device buffers (default 1, 0 disables retention)",
    )
    deploy.add_argument(
        "--serving-device",
        help="comma-separated jax device indices to pin the prepared "
        "serving state (resident sharded item factors) to, e.g. '0' or "
        "'0,1'; with --workers the list is dealt round-robin across "
        "workers (default: auto round-robin over all visible devices)",
    )
    deploy.add_argument(
        "--metrics-port", type=int, default=0,
        help="also serve this process's /metrics + /healthz + /readyz + "
        "/debug/traces.json on a dedicated sideband port — the "
        "individually-scrapable address an SO_REUSEPORT worker needs "
        "for exact fleet federation (0 disables; with --workers the "
        "supervisor assigns one per worker automatically when "
        "--collector-url is set)",
    )
    deploy.add_argument(
        "--collector-url",
        help="with --workers: telemetry collector base URL "
        "(pio collector) to auto-register every worker's sideband "
        "/metrics address with",
    )
    deploy.set_defaults(func=cmd_deploy)

    undeploy = sub.add_parser("undeploy", help="stop a deployed server")
    undeploy.add_argument("--ip", default="localhost")
    undeploy.add_argument("--port", type=int, default=8000)
    undeploy.set_defaults(func=cmd_undeploy)

    # servers
    es = sub.add_parser("eventserver", help="start the Event Server")
    es.add_argument("--ip", default="localhost")
    es.add_argument("--port", type=int, default=7070)
    es.add_argument("--stats", action="store_true")
    es.add_argument(
        "--workers", type=int, default=1,
        help="worker processes sharing the port via SO_REUSEPORT "
        "(requires multi-process-shared storage: sqlite file or gateway)",
    )
    es.add_argument(
        "--reuse-port", action="store_true",
        help="bind with SO_REUSEPORT (set automatically for workers)",
    )
    es.add_argument(
        "--transport", choices=("async", "threaded"), default="async",
        help="REST frontend: 'async' = event loop + bounded handler "
        "pool; 'threaded' = stdlib thread-per-connection fallback",
    )
    es.add_argument(
        "--no-compact", action="store_true",
        help="disable the background segment compactor (cold event "
        "ranges stay in the row store; see 'pio compact')",
    )
    es.add_argument(
        "--metrics-port", type=int, default=0,
        help="also serve this process's observability surface on a "
        "dedicated sideband port (api/sideband.py) — the "
        "individually-scrapable address an SO_REUSEPORT worker needs "
        "for exact fleet federation (0 disables)",
    )
    es.add_argument(
        "--metrics-access-key", default="",
        help="access key gating the sideband's /debug/traces.json "
        "(required for a non-loopback --ip — the span dump carries "
        "entity ids and timings)",
    )
    es.set_defaults(func=cmd_eventserver)

    cp = sub.add_parser(
        "compact",
        help="seal cold event ranges into immutable columnar segments",
    )
    cp.add_argument("--app", help="app name (default: every app)")
    cp.add_argument("--channel", help="channel name (with --app)")
    cp.add_argument(
        "--interval", type=float, default=0.0,
        help="run as a daemon at this period in seconds "
        "(default: one round, then exit)",
    )
    cp.add_argument(
        "--cold-s", type=float, default=300.0,
        help="events older than this are sealable (default 300)",
    )
    cp.add_argument(
        "--min-events", type=int, default=4096,
        help="skip rounds that would seal fewer events (default 4096)",
    )
    cp.add_argument(
        "--grace-s", type=float, default=600.0,
        help="sealed rows stay physically present this long so "
        "in-flight scans never lose them (default 600)",
    )
    cp.set_defaults(func=cmd_compact)

    gw = sub.add_parser(
        "storagegateway",
        help="serve this host's storage to remote processes (http backend)",
    )
    gw.add_argument("--ip", default="localhost")
    gw.add_argument("--port", type=int, default=7077)
    gw.add_argument("--secret", default="")
    gw.add_argument(
        "--transport", choices=("async", "threaded"), default="async",
        help="REST transport (event-loop frontend, or the stdlib "
        "thread-per-connection fallback)",
    )
    gw.set_defaults(func=cmd_storagegateway)

    sc = sub.add_parser(
        "storagecluster",
        help="operate the partitioned gateway tier (topology, resync)",
    )
    sc_sub = sc.add_subparsers(dest="cluster_command")
    sc_status = sc_sub.add_parser(
        "status", help="per-node topology, breaker and staleness table"
    )
    sc_status.add_argument(
        "--source", default="", help="storage source name (default: EVENTDATA)"
    )
    sc_resync = sc_sub.add_parser(
        "resync",
        help="replay missed rows onto recovered stale nodes from peers",
    )
    sc_resync.add_argument("--source", default="")
    sc_resync.add_argument(
        "--full", action="store_true",
        help="replay tables in full instead of above each node's "
        "event-time high-water mark (recovers out-of-order event times)",
    )
    sc.set_defaults(func=cmd_storagecluster, cluster_command="status")

    tr = sub.add_parser(
        "trace",
        help="dump request traces from a server's /debug/traces.json",
    )
    tr.add_argument(
        "--url", default="http://localhost:8000",
        help="server base URL (engine server :8000, event server :7070, "
        "storage gateway :7077)",
    )
    tr.add_argument("--trace-id", default="", help="filter to one trace")
    tr.add_argument(
        "--access-key", default="",
        help="access key (event/engine server gating)",
    )
    tr.add_argument(
        "--secret", default="", help="shared secret (storage gateway)"
    )
    tr.add_argument(
        "--json", action="store_true", help="raw span JSON, not the tree"
    )
    tr.add_argument(
        "--collector", default="",
        help="telemetry collector base URL: read the fleet's STITCHED "
        "cross-process spans from its /api/traces.json instead of one "
        "server's ring (each span shows the process it came from)",
    )
    tr.set_defaults(func=cmd_trace)

    pf = sub.add_parser(
        "profile",
        help="capture an on-demand jax.profiler trace from a running "
        "server (POST /debug/profile) and save the archive",
    )
    pf.add_argument(
        "--url", default="http://localhost:8000",
        help="server base URL (engine server :8000, event server :7070, "
        "storage gateway :7077); with --collector, the TARGET the "
        "collector should capture",
    )
    pf.add_argument(
        "--seconds", type=float, default=2.0,
        help="capture window (bounded server-side at 120s)",
    )
    pf.add_argument(
        "--out", default="profile.zip",
        help="where to write the zipped trace archive",
    )
    pf.add_argument(
        "--access-key", default="",
        help="access key (event/engine server gating)",
    )
    pf.add_argument(
        "--secret", default="",
        help="shared secret (storage gateway; collector admin secret "
        "with --collector)",
    )
    pf.add_argument(
        "--collector", default="",
        help="telemetry collector base URL: relay the capture through "
        "its POST /api/profile (the collector forwards its own "
        "credentials to the target)",
    )
    pf.set_defaults(func=cmd_profile)

    rp = sub.add_parser(
        "replay",
        help="re-run a prediction capture against a persisted model "
        "instance and report divergence (jaccard@n, rank displacement, "
        "score delta)",
    )
    rp.add_argument(
        "--capture", required=True,
        help="capture file: a saved /debug/predictions.json dump or "
        "JSON-lines records (workflow/quality.py format)",
    )
    rp.add_argument("-v", "--variant", default="engine.json")
    rp.add_argument(
        "--engine-instance-id",
        help="target instance (default: latest COMPLETED)",
    )
    rp.add_argument(
        "--version",
        help="replay only records captured from this model version",
    )
    rp.add_argument(
        "--num", type=int, default=0,
        help="replay only the newest N records (default: all)",
    )
    rp.add_argument(
        "--batch", type=int, default=64,
        help="queries per serve_batch call during replay",
    )
    rp.add_argument("--json", help="write the full report JSON here")
    rp.add_argument(
        "--fail-on-divergence", action="store_true",
        help="exit nonzero when any replayed query diverged",
    )
    rp.add_argument(
        "--serving-variant", default="",
        help="replay only records served by this experiment arm "
        "(records carry 'variant' when captured under a running "
        "experiment; -v/--variant remains the engine variant JSON)",
    )
    rp.set_defaults(func=cmd_replay)

    top = sub.add_parser(
        "top",
        help="live console over a fleet's /metrics + /healthz + /readyz",
    )
    top.add_argument(
        "--url", action="append",
        help="server base URL (repeatable: one row per server — event "
        "servers, engine servers, storage gateways, any mix)",
    )
    top.add_argument(
        "--collector", default="",
        help="telemetry collector base URL: render the WHOLE fleet "
        "from its /api/fleet.json (one endpoint, SLO alert footer) "
        "instead of per-server scrapes",
    )
    top.add_argument(
        "--interval", type=float, default=2.0,
        help="seconds between refreshes (default 2)",
    )
    top.add_argument(
        "--once", action="store_true",
        help="print one frame and exit (scripting)",
    )
    top.set_defaults(func=cmd_top)

    col = sub.add_parser(
        "collector",
        help="fleet telemetry collector: federated /metrics, "
        "cross-process trace stitching, SLO burn-rate alerts",
    )
    col.add_argument("--ip", default="localhost")
    col.add_argument("--port", type=int, default=7078)
    col.add_argument(
        "--targets", action="append", default=None,
        help="fleet process base URL to poll (repeatable); every "
        "worker needs its OWN address — give SO_REUSEPORT workers "
        "sideband ports via --metrics-port / --collector-url",
    )
    col.add_argument(
        "--targets-file",
        help="file of target URLs, one per line (# comments allowed)",
    )
    col.add_argument(
        "--interval", type=float, default=2.0,
        help="seconds between poll sweeps (default 2)",
    )
    col.add_argument(
        "--retention", type=int, default=360,
        help="exposition snapshots retained per target (default 360 ≈ "
        "12 min at the default interval; size to cover the slowest "
        "SLO window for full-fidelity slow burns)",
    )
    col.add_argument(
        "--slo-file",
        help="JSON list of SLO declarations (utils/telemetry.SLODef "
        "fields; default: the stock serving-availability / "
        "serving-latency / ingest-errors SLOs)",
    )
    col.add_argument(
        "--access-key", default="",
        help="access key forwarded on span pulls (event/engine servers "
        "gate /debug/traces.json behind it)",
    )
    col.add_argument(
        "--secret", default="",
        help="shared secret forwarded on span pulls (storage gateways)",
    )
    col.add_argument(
        "--admin-secret", default="",
        help="gate POST /api/targets registration (required for "
        "non-loopback --ip)",
    )
    col.add_argument(
        "--transport", choices=("async", "threaded"), default="async",
    )
    col.set_defaults(func=cmd_collector)

    exp = sub.add_parser(
        "experiment",
        help="online experimentation plane: sticky multi-variant "
        "serving with sequential-test-driven promotion "
        "(workflow/experiment.py)",
    )
    exp_sub = exp.add_subparsers(dest="experiment_command", required=True)
    exp_common = {
        "--url": dict(
            default="http://localhost:8000",
            help="engine server base URL (default "
            "http://localhost:8000)",
        ),
        "--accesskey": dict(
            dest="access_key", default="",
            help="engine server access key (required when the server "
            "was deployed with one)",
        ),
        "--collector": dict(
            default="",
            help="telemetry collector base URL: also register/read the "
            "experiment there for fleet-wide sequential evaluation",
        ),
        "--secret": dict(
            default="",
            help="collector admin secret (POST /api/experiments.json "
            "is admin-gated)",
        ),
        "--workers": dict(
            type=int, default=1,
            help="worker processes behind the URL (SO_REUSEPORT fleet): "
            "start/stop re-POST the idempotent request and require "
            "max(3, 2*workers) consecutive agreeing reads before "
            "declaring the fleet converged (the promotion tier's "
            "FleetTarget idiom)",
        ),
    }
    exp_start = exp_sub.add_parser(
        "start", help="deploy all arms warm and start allocating"
    )
    exp_start.add_argument(
        "--spec", help="ExperimentSpec JSON file (overrides the flags)"
    )
    exp_start.add_argument("--name", default="", help="experiment name")
    exp_start.add_argument(
        "--variant-id", action="append",
        help="arm engine-instance id (repeat >= 2 times; the FIRST is "
        "control)",
    )
    exp_start.add_argument(
        "--split", default="",
        help="comma-separated traffic fractions, one per arm "
        "(default: uniform)",
    )
    exp_start.add_argument(
        "--salt", default="",
        help="allocation salt (default: the experiment name — same "
        "name, same assignment across restarts)",
    )
    exp_start.add_argument(
        "--user-field", default="user",
        help="query JSON field used as the sticky key (default "
        "'user'; absent, the whole query is the key)",
    )
    exp_start.add_argument(
        "--horizon-s", type=float, default=3600.0,
        help="experiment horizon in seconds (default 3600)",
    )
    exp_start.add_argument(
        "--alpha", type=float, default=0.05,
        help="sequential-test type-I error bound (default 0.05)",
    )
    exp_start.add_argument(
        "--on-inconclusive", choices=("keep-control", "keep-live"),
        default="keep-control",
        help="verdict when the horizon passes undecided",
    )
    exp_status = exp_sub.add_parser(
        "status", help="current experiment + sequential-test report"
    )
    exp_stop = exp_sub.add_parser(
        "stop", help="stop allocating; drain losing arms"
    )
    exp_stop.add_argument(
        "--winner", default="",
        help="retain this arm warm; every other non-live arm drains "
        "to release",
    )
    for sp in (exp_start, exp_status, exp_stop):
        for flag, kwargs in exp_common.items():
            sp.add_argument(flag, **kwargs)
    exp.set_defaults(func=cmd_experiment)

    admin = sub.add_parser("adminserver", help="start the admin server")
    admin.add_argument("--ip", default="localhost")
    admin.add_argument("--port", type=int, default=7071)
    admin.set_defaults(func=cmd_adminserver)

    dash = sub.add_parser("dashboard", help="start the evaluation dashboard")
    dash.add_argument("--ip", default="localhost")
    dash.add_argument("--port", type=int, default=9000)
    dash.set_defaults(func=cmd_dashboard)

    # template / run
    tpl = sub.add_parser("template", help="engine template gallery")
    tpl_sub = tpl.add_subparsers(dest="template_command", required=True)
    tpl_sub.add_parser("list")
    tpl_get = tpl_sub.add_parser(
        "get",
        help="packaged template by name, or user/repo from GitHub",
    )
    tpl_get.add_argument("name")
    tpl_get.add_argument("directory", nargs="?")
    tpl_get.add_argument("--app-name", default="MyApp")
    tpl_get.add_argument(
        "--ref", default="", help="git tag to fetch (default: latest)"
    )
    tpl_get.add_argument(
        "--sha256", default="",
        help="pin the downloaded archive to this checksum",
    )
    tpl.set_defaults(func=cmd_template)

    run = sub.add_parser(
        "run", help="run an arbitrary fn(ctx) under the workflow env"
    )
    run.add_argument("main", help="module path of a fn(ctx) callable")
    run.set_defaults(func=cmd_run)

    # export / import / status / version
    exp = sub.add_parser(
        "export", help="export events to a JSON-lines or Parquet file"
    )
    exp.add_argument("--app-name", required=True)
    exp.add_argument("--output", required=True)
    exp.add_argument("--channel")
    exp.add_argument(
        "--format", choices=("json", "parquet"), default="json",
        help="output format (reference EventsToFile.scala:85-100)",
    )
    exp.set_defaults(func=cmd_export)

    imp = sub.add_parser(
        "import",
        help="import events from a JSON-lines or Parquet file (auto-detected)",
    )
    imp.add_argument("--app-name", required=True)
    imp.add_argument("--input", required=True)
    imp.add_argument("--channel")
    imp.set_defaults(func=cmd_import)

    sub.add_parser("status", help="check storage config").set_defaults(
        func=cmd_status
    )
    sub.add_parser(
        "shell", help="interactive Python with the pio env loaded"
    ).set_defaults(func=cmd_shell)
    sub.add_parser("version").set_defaults(func=cmd_version)
    upg = sub.add_parser(
        "upgrade", help="check whether a newer release is available"
    )
    upg.add_argument("--url", default="", help="override the release index")
    upg.set_defaults(func=cmd_upgrade)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    # structured logging (utils/logging.py): text by default, JSON
    # lines with trace/span correlation under PIO_LOG_FORMAT=json
    from predictionio_tpu.utils.logging import setup_logging

    setup_logging(level=logging.INFO)
    args = build_parser().parse_args(argv)
    try:
        return args.func(args)
    except CommandError as e:
        print(str(e), file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
