"""``pio collector`` — the fleet telemetry collector daemon.

The HTTP face of :class:`utils.telemetry.Collector`: one standalone
process polls every fleet member's existing public endpoints
(``/metrics``, ``/healthz``, ``/readyz``, ``/debug/traces.json``) and
serves the merged operator view:

- ``GET  /metrics``          — FEDERATED fleet exposition (counters and
  histogram buckets summed exactly across targets, gauges per-instance
  via an added ``instance`` label) plus the collector's own families;
- ``GET  /api/fleet.json``   — per-target and fleet-level rates and
  p50/p99-over-time computed from snapshot deltas (``?window=S``);
- ``GET  /api/traces.json``  — cross-process stitched spans
  (``?traceId=…&limit=N``), rendered by ``pio trace --collector``;
- ``GET  /api/alerts.json``  — the SLO burn-rate report and firing
  alerts;
- ``GET  /api/targets.json`` / ``POST /api/targets`` (``{"url": …}``)
  — the target registry; ``tools/fleet.py`` auto-registers its workers
  here;
- ``GET  /api/experiments.json`` / ``POST /api/experiments.json``
  (``{"spec": {…}}`` to register, ``{"remove": name}`` to drop) — the
  experiment registry + the sequential-test reports; POST is
  admin-gated (a registration drives an automatic promotion decision);
- ``GET  /healthz`` / ``GET /readyz`` — the collector's own health
  (ready = the poll loop scraped something recently and is not
  stalled).

Binding a non-loopback interface without ``--admin-secret`` refuses
(the gateway's posture): the stitched span dump aggregates every fleet
member's gated debug surface.
"""

from __future__ import annotations

import concurrent.futures
import hmac
import json
import logging
from typing import Optional

from predictionio_tpu.api.aio_http import TRANSPORTS, make_http_server
from predictionio_tpu.utils import health as _health
from predictionio_tpu.utils import metrics as _metrics
from predictionio_tpu.utils.telemetry import Collector

logger = logging.getLogger(__name__)

__all__ = ["CollectorServer", "DEFAULT_PORT"]

DEFAULT_PORT = 7078  # beside the storage gateway's 7077

_LOOPBACK_IPS = ("localhost", "127.0.0.1", "::1")


class CollectorServer:
    """The collector's HTTP frontend. Handlers are pure reads of the
    Collector's in-memory state (no storage, no network), so they run
    inline on the event loop like the sideband's."""

    def __init__(
        self,
        collector: Collector,
        ip: str = "localhost",
        port: int = DEFAULT_PORT,
        admin_secret: str = "",
        transport: str = "async",
    ):
        if not admin_secret and ip not in _LOOPBACK_IPS:
            raise ValueError(
                f"refusing to bind the collector on {ip!r} without "
                "--admin-secret: the stitched span dump aggregates every "
                "fleet member's gated debug surface"
            )
        if transport not in TRANSPORTS:
            raise ValueError(
                f"unknown transport {transport!r} "
                f"(expected one of {TRANSPORTS})"
            )
        self.collector = collector
        self.admin_secret = admin_secret
        self._transport = transport
        # profile captures block for their whole window; one worker
        # serializes them (jax.profiler cannot run two anyway)
        self._profile_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="collector-profile"
        )
        self._http = make_http_server(
            self._handle, ip, port, "Collector", transport=transport
        )
        # ready = the poll loop delivered a scrape recently; the margin
        # covers one slow sweep over a fleet with a dead member
        self._ready_probe = _health.TTLProbe("poll", self._probe_poll)

    def _probe_poll(self) -> None:
        age = self.collector.last_poll_age_s()
        budget = max(10.0, 3.0 * self.collector.poll_interval_s)
        if not self.collector.target_urls():
            return  # an empty registry is idle, not broken
        if age is None:
            raise RuntimeError("no target scraped yet")
        if age > budget:
            raise RuntimeError(
                f"newest scrape is {age:.1f}s old (budget {budget:.1f}s)"
            )

    def _authorized(self, query, payload: Optional[dict] = None) -> bool:
        if not self.admin_secret:
            return True
        given = (query or {}).get("secret", "")
        if not given and payload:
            given = str(payload.get("secret") or "")
        return hmac.compare_digest(given, self.admin_secret)

    def _handle(self, method, path, query, body, form=None, headers=None):
        c = self.collector
        if path == "/healthz" and method == "GET":
            return 200, _health.liveness()
        if path == "/readyz" and method == "GET":
            ok, payload = _health.readiness((self._ready_probe,))
            return (200 if ok else 503), payload
        if path == "/metrics" and method == "GET":
            return 200, self._render_metrics(), _metrics.render_content_type()
        if path == "/api/fleet.json" and method == "GET":
            try:
                window_s = float((query or {}).get("window", 60.0))
            except (TypeError, ValueError):
                return 400, {"message": "invalid window"}
            return 200, c.fleet_json(window_s=window_s)
        if path == "/api/traces.json" and method == "GET":
            q = query or {}
            try:
                limit = int(q.get("limit", 4096))
            except (TypeError, ValueError):
                return 400, {"message": "invalid limit"}
            return 200, c.traces_json(q.get("traceId") or None, limit)
        if path == "/api/alerts.json" and method == "GET":
            return 200, c.alerts_json()
        if path == "/api/profile" and method == "POST":
            # trigger + fetch one bounded profiler capture on a fleet
            # target (the target's own secret gating still applies —
            # the collector forwards its configured credentials).
            # Admin-gated: the archive is a device timeline of the
            # target's workload.
            try:
                payload = json.loads((body or b"{}").decode("utf-8"))
            except (ValueError, UnicodeDecodeError) as e:
                return 400, {"message": f"invalid JSON body: {e}"}
            if not isinstance(payload, dict):
                return 400, {"message": "body must be a JSON object"}
            if not self._authorized(query, payload):
                return 401, {"message": "invalid or missing secret"}
            target = str(payload.get("target") or "")
            if target not in c.target_urls():
                return 400, {
                    "message": "target must be a registered collector "
                    f"target (have {c.target_urls()})"
                }
            try:
                seconds = float(payload.get("seconds", 2.0))
            except (TypeError, ValueError):
                return 400, {"message": "invalid seconds"}
            # a capture blocks for its whole window — off the event
            # loop (the async transport awaits the returned future;
            # the threaded transport's per-connection thread may block)
            fut = self._profile_pool.submit(
                self._do_capture, target, seconds
            )
            if self._transport == "async":
                return fut
            return fut.result()
        if path == "/api/experiments.json" and method == "GET":
            return 200, c.experiments_json()
        if path == "/api/experiments.json" and method == "POST":
            # register / remove an experiment for sequential evaluation.
            # Admin-gated: an experiment registration drives an
            # automatic promotion decision downstream.
            try:
                payload = json.loads((body or b"{}").decode("utf-8"))
            except (ValueError, UnicodeDecodeError) as e:
                return 400, {"message": f"invalid JSON body: {e}"}
            if not isinstance(payload, dict):
                return 400, {"message": "body must be a JSON object"}
            if not self._authorized(query, payload):
                return 401, {"message": "invalid or missing secret"}
            if payload.get("remove"):
                removed = c.remove_experiment(str(payload["remove"]))
                return 200, {"removed": removed}
            from predictionio_tpu.workflow.experiment import ExperimentSpec

            try:
                spec = ExperimentSpec.from_json(
                    payload.get("spec") or {
                        k: v
                        for k, v in payload.items()
                        if k != "secret"
                    }
                )
            except ValueError as e:
                return 400, {"message": str(e)}
            added = c.register_experiment(spec)
            return 200, {"added": added, "experiment": spec.name}
        if path == "/api/targets.json" and method == "GET":
            return 200, {"targets": c.target_urls()}
        if path == "/api/targets" and method == "POST":
            try:
                payload = json.loads((body or b"{}").decode("utf-8"))
            except (ValueError, UnicodeDecodeError) as e:
                return 400, {"message": f"invalid JSON body: {e}"}
            if not isinstance(payload, dict):
                return 400, {"message": "body must be a JSON object"}
            if not self._authorized(query, payload):
                return 401, {"message": "invalid or missing secret"}
            url = str(payload.get("url") or "")
            if not url:
                return 400, {"message": "missing url"}
            try:
                if payload.get("remove"):
                    removed = c.remove_target(url)
                    return 200, {
                        "removed": removed, "targets": c.target_urls()
                    }
                added = c.add_target(url)
            except ValueError as e:
                return 400, {"message": str(e)}
            return 200, {"added": added, "targets": c.target_urls()}
        return 404, {"message": f"unknown route {method} {path}"}

    def _do_capture(self, target: str, seconds: float):
        try:
            return 200, self.collector.capture_profile(target, seconds)
        except Exception as e:
            logger.warning(
                "profile capture on %s failed", target, exc_info=True
            )
            return 502, {"message": f"capture on {target} failed: {e}"}

    def _render_metrics(self) -> str:
        """Federated fleet families first, then this process's OWN
        families (``pio_collector_*``, the SLO gauges, heartbeats) that
        federation did not already cover — one HELP/TYPE per family
        name, so the output stays valid exposition even when an
        operator registers the collector as its own target."""
        federated = self.collector.federated_families()
        lines = [self.collector.render_federated().rstrip("\n")]
        for fam in _metrics.get_registry().families():
            if fam.name in federated:
                continue
            lines.extend(fam.render())
        return "\n".join(line for line in lines if line) + "\n"

    @property
    def port(self) -> int:
        return self._http.port

    def start(self) -> "CollectorServer":
        self._http.start()
        return self

    def serve_forever(self) -> None:
        self._http.serve_forever()

    def shutdown(self) -> None:
        self._http.shutdown()
        # wait=False: an in-flight capture must not wedge teardown
        self._profile_pool.shutdown(wait=False)
