"""Supervised SO_REUSEPORT worker fleets for ``pio deploy --workers``.

The pre-round-13 fleet launcher spawned N worker processes and simply
waited: a worker that crashed (OOM, a poisoned model load, a device
fault) left the fleet silently degraded until an operator noticed the
qps drop. This module owns the supervision loop instead:

- a worker that exits NONZERO outside shutdown is restarted with capped
  exponential backoff (1s, 2s, 4s, ... up to ``backoff_cap_s``); a
  worker that then stays alive ``healthy_reset_s`` gets its backoff
  reset, so a one-off crash recovers fast while a crash-looping worker
  cannot hot-spin the supervisor;
- every restart is counted in
  ``pio_fleet_worker_restarts_total{worker}`` (the supervisor's process
  registry; ``pio top`` renders the family as its RESTART column) and
  logged with the exit code;
- a worker that exits ZERO (a clean /stop undeploy) is intentional and
  is NOT restarted — when every worker has exited cleanly the
  supervisor returns 0;
- startup keeps the pre-existing grace semantics: workers that die
  within the bind-grace window mean a configuration failure (port held,
  model missing) and abort the whole fleet rather than restart-looping
  a doomed command.

The loop is shutdown-aware by construction (stop-event idiom — the
tools/ while-True lint's sanctioned shape): SIGTERM/SIGINT set the stop
event, terminate the children, and the supervisor returns.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request
from typing import Callable, List, Optional, Sequence

from predictionio_tpu.utils import metrics as _metrics

logger = logging.getLogger(__name__)

__all__ = ["register_fleet_targets", "run_worker_fleet"]


def register_fleet_targets(
    collector_url: str,
    worker_urls: Sequence[str],
    timeout_s: float = 5.0,
    admin_secret: str = "",
) -> int:
    """Register every worker's scrape address with a local telemetry
    collector (``POST /api/targets`` — idempotent, so supervisor
    restarts re-register harmlessly). Returns how many registrations
    succeeded; failures log and never fail the fleet — a collector
    being down is an observability gap, not a serving outage."""
    ok = 0
    base = collector_url.rstrip("/")
    for url in worker_urls:
        payload: dict = {"url": url}
        if admin_secret:
            payload["secret"] = admin_secret
        req = urllib.request.Request(
            base + "/api/targets",
            data=json.dumps(payload).encode("utf-8"),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout_s):
                ok += 1
        except Exception as e:
            logger.warning(
                "collector registration of %s with %s failed: %s",
                url, collector_url, e,
            )
    if ok:
        logger.info(
            "registered %d/%d worker(s) with collector %s",
            ok, len(worker_urls), collector_url,
        )
    return ok


def _restarts_counter() -> "_metrics.Counter":
    return _metrics.get_registry().counter(
        "pio_fleet_worker_restarts_total",
        "Crashed fleet workers restarted by the supervisor, by worker "
        "slot",
        labels=("worker",),
    )


def run_worker_fleet(
    spawn: Callable[[int], "object"],
    workers: int,
    *,
    fleet_name: str = "fleet",
    grace_s: float = 2.0,
    poll_s: float = 0.5,
    backoff_base_s: float = 1.0,
    backoff_cap_s: float = 30.0,
    healthy_reset_s: float = 60.0,
    stop_event: Optional[threading.Event] = None,
    install_signal_handlers: bool = True,
    on_started: Optional[Callable[[], None]] = None,
    collector_url: Optional[str] = None,
    worker_urls: Optional[Sequence[str]] = None,
) -> int:
    """Spawn ``workers`` processes via ``spawn(slot)`` and supervise
    them until shutdown. Returns the fleet's exit code (0 on a clean
    stop, the first nonzero worker code when workers exited on their
    own uncleanly at shutdown, 1 on a startup failure).

    ``spawn`` must return a ``subprocess.Popen``-compatible object
    (``poll()``, ``terminate()``, ``wait()``, ``returncode``); tests
    drive the supervisor with lightweight stand-in processes.
    """
    stop = stop_event if stop_event is not None else threading.Event()
    procs: List[object] = [spawn(w) for w in range(workers)]
    # per-slot restart state: consecutive crash count + last spawn time
    consecutive = [0] * workers
    spawned_at = [time.monotonic()] * workers
    # a slot whose worker exited CLEANLY stays retired
    retired = [False] * workers

    def _terminate_all() -> None:
        for p in procs:
            if p is not None and p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    logger.debug("terminate failed", exc_info=True)

    if install_signal_handlers:
        import signal

        def forward(signum, frame):
            stop.set()
            _terminate_all()

        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                signal.signal(sig, forward)
            except ValueError:  # not the main thread (tests)
                break

    # startup grace: a worker dead this early failed to START (bind
    # conflict, missing model) — abort the fleet, do not restart-loop a
    # doomed configuration
    if not stop.wait(grace_s):
        dead = [p for p in procs if p.poll() is not None]
        if dead and not stop.is_set():
            _terminate_all()
            for p in procs:
                p.wait()
            logger.error(
                "%s: %d/%d workers failed to start; aborting",
                fleet_name, len(dead), workers,
            )
            return 1
    if stop.is_set():
        _terminate_all()
        rc = 0
        for p in procs:
            code = p.wait()
            if code and code > 0:
                rc = code
        return rc
    if on_started is not None:
        on_started()
    if collector_url and worker_urls:
        # auto-register each worker's sideband scrape address with the
        # local telemetry collector (idempotent; failure is logged, not
        # fatal — see register_fleet_targets)
        register_fleet_targets(collector_url, worker_urls)

    rc = 0
    # per-slot pending-restart deadlines: backoff is tracked, never
    # slept inline — a 30s backoff on one crash-looping slot must not
    # stall crash DETECTION (and restarts) on every other slot
    restart_at: list = [None] * workers
    while not stop.is_set():
        now = time.monotonic()
        for w, p in enumerate(procs):
            if retired[w]:
                continue
            if restart_at[w] is not None:
                if now >= restart_at[w]:
                    restart_at[w] = None
                    procs[w] = spawn(w)
                    spawned_at[w] = time.monotonic()
                    _restarts_counter().labels(worker=str(w)).inc()
                continue
            if p.poll() is None:
                continue
            code = p.returncode
            if code == 0:
                # intentional exit (undeploy /stop): retire the slot
                logger.info(
                    "%s: worker %d exited cleanly; not restarting",
                    fleet_name, w,
                )
                retired[w] = True
                continue
            if now - spawned_at[w] >= healthy_reset_s:
                consecutive[w] = 0
            delay = min(
                backoff_cap_s, backoff_base_s * (2 ** consecutive[w])
            )
            consecutive[w] += 1
            restart_at[w] = now + delay
            logger.warning(
                "%s: worker %d crashed (rc=%s); restart %d in %.1fs",
                fleet_name, w, code, consecutive[w], delay,
            )
        if all(retired):
            return 0
        if stop.wait(poll_s):
            break

    _terminate_all()
    for w, p in enumerate(procs):
        code = p.wait()
        # a worker killed by the signal we forwarded is a clean stop,
        # not a failure bubbling up as -SIGTERM
        if code and code > 0:
            rc = code or rc
    return rc
