"""Tools layer: the ``pio`` console, admin commands, admin REST server,
evaluation dashboard, and event export/import.

Capability parity with the reference ``tools`` module
(tools/src/main/scala/io/prediction/tools/): where the reference launches
every workload through spark-submit subprocesses (Runner.scala:36), the
single-controller runtime runs train/eval/deploy in process — the process
boundary collapses to a function call, and the ``pio`` entry point is
``python -m predictionio_tpu.tools.cli``.
"""
