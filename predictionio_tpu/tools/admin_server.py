"""Admin REST server on :7071.

Capability parity with the reference AdminAPI
(tools/src/main/scala/io/prediction/tools/admin/AdminAPI.scala:66-141):

  GET    /                     -> {"status": "alive"}
  GET    /cmd/app              -> list apps
  POST   /cmd/app              -> create app {"name", "id"?, "description"?}
  DELETE /cmd/app/<name>       -> delete app
  DELETE /cmd/app/<name>/data  -> wipe app event data

Backed by the shared CommandClient (the reference's CommandClient.scala).
"""

from __future__ import annotations

import json
import logging
import urllib.parse
from typing import Optional, Tuple

from predictionio_tpu.api.http import JsonHTTPServer
from predictionio_tpu.data.storage import Storage, get_storage
from predictionio_tpu.tools.commands import (
    AppDescription,
    CommandClient,
    CommandError,
)

logger = logging.getLogger(__name__)


def _describe(d: AppDescription, compaction: Optional[dict] = None) -> dict:
    out = {
        "name": d.app.name,
        "id": d.app.id,
        "description": d.app.description or "",
        "accessKeys": [
            {"key": k.key, "events": list(k.events)} for k in d.access_keys
        ],
        "channels": [{"name": c.name, "id": c.id} for c in d.channels],
    }
    if compaction is not None:
        # segment-tier observability (data/storage/segments.py): how
        # much of the app's event store scans at mmap rate
        out["compaction"] = {
            "segments": compaction["segments"],
            "compactedEvents": compaction["segmentEvents"],
            "compactedFraction": round(compaction["compactedFraction"], 6),
            "lastCompactionMs": compaction["lastCompactionMs"],
        }
    return out


class AdminAPI:
    def __init__(self, storage: Optional[Storage] = None):
        from predictionio_tpu.data.storage.segments import (
            CachedCompactionStatus,
        )

        self.storage = storage or get_storage()
        self.client = CommandClient(self.storage)
        # stats cost COUNT(*) scans per app; shared TTL cache so
        # listing-happy dashboards can't hammer the store
        self._compaction_status = CachedCompactionStatus(self.storage)

    def handle(self, method, path, query=None, body=None, form=None) -> Tuple[int, dict]:
        try:
            return self._route(method, path, body)
        except CommandError as e:
            return 400, {"status": 1, "message": str(e)}
        except Exception as e:
            logger.exception("admin error on %s %s", method, path)
            return 500, {"status": 1, "message": str(e)}

    def _route(self, method, path, body) -> Tuple[int, dict]:
        parts = [p for p in path.strip("/").split("/") if p]
        if not parts:
            if method == "GET":
                return 200, {"status": "alive"}
            return 405, {"message": "Method not allowed."}
        if parts[0] != "cmd" or len(parts) < 2 or parts[1] != "app":
            return 404, {"message": "Not Found"}

        if len(parts) == 2:
            if method == "GET":
                compaction = self._compaction_status.get()
                return 200, {
                    "status": 0,
                    "apps": [
                        _describe(d, compaction.get(d.app.name))
                        for d in self.client.app_list()
                    ],
                }
            if method == "POST":
                try:
                    payload = json.loads((body or b"{}").decode("utf-8"))
                except json.JSONDecodeError as e:
                    return 400, {"status": 1, "message": str(e)}
                if "name" not in payload:
                    return 400, {"status": 1, "message": "name is required"}
                try:
                    app_id = int(payload.get("id") or 0)
                except (TypeError, ValueError):
                    return 400, {"status": 1, "message": "id must be an integer"}
                d = self.client.app_new(
                    payload["name"],
                    app_id=app_id,
                    description=payload.get("description"),
                )
                return 200, {"status": 0, **_describe(d)}
            return 405, {"message": "Method not allowed."}

        app_name = urllib.parse.unquote(parts[2])
        if len(parts) == 3 and method == "DELETE":
            self.client.app_delete(app_name)
            return 200, {"status": 0, "message": f"App {app_name} deleted."}
        if len(parts) == 4 and parts[3] == "data" and method == "DELETE":
            self.client.app_data_delete(app_name)
            return 200, {
                "status": 0,
                "message": f"Data of app {app_name} deleted.",
            }
        return 404, {"message": "Not Found"}


class AdminServer(JsonHTTPServer):
    def __init__(
        self,
        ip: str = "localhost",
        port: int = 7071,
        storage: Optional[Storage] = None,
    ):
        self.api = AdminAPI(storage)
        super().__init__(self.api.handle, ip, port, "Admin Server")


def create_admin_server(
    ip: str = "localhost", port: int = 7071, storage: Optional[Storage] = None
) -> AdminServer:
    """Reference AdminServer.createAdminServer (AdminAPI.scala:128-141)."""
    return AdminServer(ip=ip, port=port, storage=storage)
