"""predictionio_tpu — a TPU-native machine-learning server framework.

A ground-up rebuild of the capability surface of PredictionIO (reference:
DrahmA/PredictionIO, Scala/Spark) on a JAX/XLA substrate:

- REST event collection into a pluggable event store (``predictionio_tpu.data``)
- engines composed from the DASE controller API — DataSource / Preparator /
  Algorithm(s) / Serving, plus Evaluation (``predictionio_tpu.controller``)
- a train -> persist -> deploy -> query lifecycle (``predictionio_tpu.workflow``,
  ``predictionio_tpu.tools``)
- metric-based evaluation with hyperparameter grid search
- TPU compute kernels (blocked implicit ALS, NaiveBayes count reductions,
  cosine top-N) under ``predictionio_tpu.ops`` running as pjit/shard_map
  programs over a `jax.sharding.Mesh` (``predictionio_tpu.parallel``).

Where the reference delegates compute to Apache Spark RDDs + MLlib, this
framework materializes event data as column-oriented host batches destined for
device-sharded arrays, and runs training/serving math as XLA programs.
"""

__version__ = "0.1.0"
