"""Step-level training checkpoints (orbax-backed).

The reference has NO mid-training checkpoint/resume — models persist only
after a full train (CoreWorkflow.scala:71-76), and distributed (P-style)
models are re-trained from scratch at deploy (SURVEY.md §5 flags this as
the gap to fill). This module adds orbax step checkpoints: a training
kernel saves its state pytree every N steps and resumes from the latest
step after interruption, with retention bounded by ``max_to_keep``.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

logger = logging.getLogger(__name__)


class StepCheckpointer:
    """Thin wrapper over orbax CheckpointManager for step/pytree saves.

    Usage in a training loop::

        ckpt = StepCheckpointer(dir, every=5)
        start = 0
        if (state := ckpt.restore_latest()) is not None:
            start, arrays = state["step"], state["arrays"]
        for step in range(start, n_steps):
            ...
            ckpt.maybe_save(step + 1, {"step": step + 1, "arrays": arrays})
        ckpt.close()
    """

    def __init__(
        self,
        directory: Optional[str],
        every: int = 1,
        max_to_keep: int = 2,
    ):
        self.directory = directory
        self.every = max(1, every)
        self._mgr = None
        if directory is not None:
            import orbax.checkpoint as ocp
            import os

            self._ocp = ocp
            self._mgr = ocp.CheckpointManager(
                os.path.abspath(directory),
                options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
            )

    @property
    def enabled(self) -> bool:
        return self._mgr is not None

    def latest_step(self) -> Optional[int]:
        if self._mgr is None:
            return None
        return self._mgr.latest_step()

    def restore_latest(self) -> Optional[Any]:
        """The latest saved pytree, or None when disabled/empty."""
        step = self.latest_step()
        if step is None:
            return None
        logger.info(
            "restoring checkpoint step %d from %s", step, self.directory
        )
        # orbax >= 0.5 requires the CheckpointArgs subclass on restore
        # (a bare restore() raises KeyError for the "default" item);
        # StandardRestore with no target tree reproduces the old
        # restore-everything behavior for our numpy/step pytrees
        return self._mgr.restore(
            step, args=self._ocp.args.StandardRestore()
        )

    def maybe_save(self, step: int, pytree: Any, force: bool = False) -> bool:
        """Save when the step hits the cadence (or force=True)."""
        if self._mgr is None:
            return False
        if not force and step % self.every != 0:
            return False
        self._mgr.save(step, args=self._ocp.args.StandardSave(pytree))
        return True

    def wait_until_finished(self) -> None:
        """Block until any in-flight async save has fully committed.

        Callers whose training step DONATES its input buffers (e.g. the
        fused ALS loop, ops/als.py donate_argnums) must call this between
        ``maybe_save(..., state)`` and the next step: orbax may copy
        device arrays to host asynchronously, and a donated buffer that
        gets overwritten mid-copy would silently corrupt the checkpoint.
        """
        if self._mgr is not None:
            self._mgr.wait_until_finished()

    def close(self) -> None:
        if self._mgr is not None:
            self._mgr.wait_until_finished()
            self._mgr.close()
            self._mgr = None
