"""Online experimentation plane: sticky multi-variant serving with a
sequential (always-valid) significance engine driving automatic
promotion.

An experiment is "a promotion whose observation window is a statistical
test": the :class:`ExperimentSpec` names >= 2 trained engine-instance
ids (the *variants* — variant ids ARE engine-instance ids), all of which
are deployed warm via the retained-state machinery, and traffic is split
with a sticky deterministic hash::

    crc32(salt + ":" + user_key) % 10000  ->  bucket  ->  variant

The allocation is a pure function of (salt, user_key, split): every
worker of an SO_REUSEPORT fleet and every restart computes the same
assignment with zero coordination, and a given user can never be
reassigned mid-experiment (0 cross-variant reassignments).

Because each variant is served by its own ``DeployedEngine``, every
existing per-version family (``pio_serving_latency_seconds{version=..}``,
``pio_serving_requests_total{version=..}``,
``pio_online_attributed_total{version=..}``) is per-variant for free, and
one federated collector scrape sees every arm of every worker.

The verdict comes from a mixture sequential probability ratio test
(mSPRT) over the attributed hit-rate — an always-valid test whose
type-I error stays <= alpha under *continuous* peeking, so the collector
may evaluate it on every poll tick exactly the way SLO burn rates are
evaluated.  A latency guardrail (windowed p99 per variant) disqualifies
a fast-converting but slow arm from winning.  Winner -> automatic
promotion through the gated :mod:`predictionio_tpu.workflow.promotion`
pipeline (shadow + observation window intact); losers -> drain/release
through the retained-LRU path; inconclusive at horizon -> configurable
keep-control.
"""

from __future__ import annotations

import dataclasses
import json
import math
import time
import zlib
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

__all__ = [
    "ALLOCATION_BUCKETS",
    "ExperimentSpec",
    "ActiveExperiment",
    "ExperimentRunner",
    "allocate",
    "allocate_bucket",
    "user_key_from_query",
    "msprt_log_lambda",
    "evaluate_sequential",
    "local_variant_stats",
]

# Allocation granularity: splits are quantised to 1/10000ths of traffic.
ALLOCATION_BUCKETS = 10000

_ON_INCONCLUSIVE = ("keep-control", "keep-live")


# --- spec ---


@dataclasses.dataclass(frozen=True)
class ExperimentSpec:
    """Declarative definition of one online experiment.

    ``variants[0]`` is the control arm.  ``split`` is the traffic
    fraction per variant (same order); it defaults to uniform and is
    normalised to sum to 1.  ``salt`` defaults to the experiment name so
    re-running an experiment under a new name reshuffles users while a
    restart of the *same* experiment never does.
    """

    name: str
    variants: Tuple[str, ...]
    split: Tuple[float, ...] = ()
    primary_metric: str = "hit_rate"
    horizon_s: float = 3600.0
    salt: str = ""
    user_field: str = "user"
    alpha: float = 0.05
    tau: float = 0.2
    min_samples: int = 50
    latency_guard_ms: float = 0.0
    latency_guard_ratio: float = 0.0
    on_inconclusive: str = "keep-control"

    def __post_init__(self):
        if not self.name:
            raise ValueError("experiment name must be non-empty")
        variants = tuple(str(v) for v in self.variants)
        if len(variants) < 2:
            raise ValueError("experiment needs >= 2 variants")
        if len(set(variants)) != len(variants):
            raise ValueError("experiment variants must be distinct")
        object.__setattr__(self, "variants", variants)
        split = tuple(float(s) for s in self.split)
        if not split:
            split = tuple(1.0 / len(variants) for _ in variants)
        if len(split) != len(variants):
            raise ValueError(
                "split must have one fraction per variant "
                f"({len(split)} != {len(variants)})"
            )
        if any(s <= 0.0 for s in split):
            raise ValueError("split fractions must be > 0")
        total = sum(split)
        object.__setattr__(self, "split", tuple(s / total for s in split))
        if not self.salt:
            object.__setattr__(self, "salt", self.name)
        if not (0.0 < self.alpha < 1.0):
            raise ValueError("alpha must be in (0, 1)")
        if self.tau <= 0.0:
            raise ValueError("tau must be > 0")
        if self.horizon_s <= 0.0:
            raise ValueError("horizon_s must be > 0")
        if self.min_samples < 1:
            raise ValueError("min_samples must be >= 1")
        if self.on_inconclusive not in _ON_INCONCLUSIVE:
            raise ValueError(
                f"on_inconclusive must be one of {_ON_INCONCLUSIVE}"
            )

    @property
    def control(self) -> str:
        return self.variants[0]

    def split_edges(self) -> Tuple[int, ...]:
        """Cumulative integer bucket edges (last edge pinned to the
        bucket count so rounding can never orphan a bucket)."""
        edges = []
        cum = 0.0
        for frac in self.split:
            cum += frac
            edges.append(int(round(cum * ALLOCATION_BUCKETS)))
        edges[-1] = ALLOCATION_BUCKETS
        return tuple(edges)

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, payload: Dict[str, Any]) -> "ExperimentSpec":
        if not isinstance(payload, dict):
            raise ValueError("experiment spec must be a JSON object")
        valid = {f.name for f in dataclasses.fields(cls)}
        unknown = set(payload) - valid
        if unknown:
            raise ValueError(
                f"unknown experiment spec keys: {sorted(unknown)}"
            )
        if "variants" in payload:
            payload = dict(payload, variants=tuple(payload["variants"]))
        if "split" in payload:
            payload = dict(payload, split=tuple(payload["split"]))
        try:
            return cls(**payload)
        except TypeError as exc:
            raise ValueError(f"invalid experiment spec: {exc}") from exc


# --- sticky allocation (pure; keep free of randomness AND clocks —
# tests/test_lint.py enforces determinism on this module) ---


def user_key_from_query(query_json: Any, user_field: str = "user") -> str:
    """Extract the sticky key from a query.  Falls back to the canonical
    JSON form of the whole query so even user-less traffic is sticky
    (identical query -> identical arm)."""
    if isinstance(query_json, dict):
        value = query_json.get(user_field)
        if value is not None:
            return str(value)
    return json.dumps(query_json, sort_keys=True, default=str)


def allocate_bucket(salt: str, user_key: str) -> int:
    """``crc32(salt + ":" + user_key) % 10000`` — the entire allocation
    contract.  Stateless and deterministic, so every worker and every
    restart agrees without coordination."""
    return zlib.crc32(
        (str(salt) + ":" + str(user_key)).encode("utf-8")
    ) % ALLOCATION_BUCKETS


def allocate(spec: ExperimentSpec, user_key: str) -> str:
    """Map a user key to its (permanent) variant id."""
    bucket = allocate_bucket(spec.salt, user_key)
    for vid, edge in zip(spec.variants, spec.split_edges()):
        if bucket < edge:
            return vid
    return spec.variants[-1]


# --- sequential significance engine ---


def msprt_log_lambda(
    conv_a: float, n_a: float, conv_b: float, n_b: float, tau: float
) -> float:
    """Log of the mixture-SPRT likelihood ratio for a two-sample
    difference in proportions, with a Gaussian mixture of scale ``tau``
    over the effect size.

    ``a`` is control, ``b`` the candidate.  Rejecting H0 (no difference)
    when ``Lambda >= 1/alpha`` keeps the type-I error <= alpha at EVERY
    peek (always-valid inference), which is what licenses evaluating it
    on each collector poll without alpha-spending bookkeeping.
    """
    if n_a <= 0 or n_b <= 0:
        return 0.0
    p_a = conv_a / n_a
    p_b = conv_b / n_b
    pooled = (conv_a + conv_b) / (n_a + n_b)
    variance = pooled * (1.0 - pooled) * (1.0 / n_a + 1.0 / n_b)
    if variance <= 0.0:
        return 0.0
    tau2 = tau * tau
    delta = p_b - p_a
    return 0.5 * math.log(variance / (variance + tau2)) + (
        delta * delta * tau2
    ) / (2.0 * variance * (variance + tau2))


def _guard_ok(
    spec: ExperimentSpec,
    p99_s: Optional[float],
    control_p99_s: Optional[float],
) -> bool:
    """Latency guardrail: a candidate may not win while its windowed p99
    violates the absolute bound (``latency_guard_ms``) or exceeds
    ``latency_guard_ratio`` x the control's p99.  Missing data passes —
    the guard disqualifies on evidence, not on absence."""
    if p99_s is None:
        return True
    if spec.latency_guard_ms > 0.0 and p99_s * 1000.0 > spec.latency_guard_ms:
        return False
    if (
        spec.latency_guard_ratio > 0.0
        and control_p99_s is not None
        and control_p99_s > 0.0
        and p99_s > spec.latency_guard_ratio * control_p99_s
    ):
        return False
    return True


def evaluate_sequential(
    spec: ExperimentSpec,
    stats: Dict[str, Dict[str, Any]],
    elapsed_s: Optional[float] = None,
) -> Dict[str, Any]:
    """One peek of the sequential test over per-variant attributed
    outcome counts.

    ``stats`` maps variant id -> ``{"converted", "miss", "requests",
    "p99_s"}`` accumulated *since the experiment started*.  Returns a
    report with ``status`` in ``("running", "decided", "horizon")``; on
    ``decided`` the ``winner`` is either a candidate that significantly
    beats control (and passes the latency guard) or the control itself
    when every candidate has significantly lost.
    """
    control = spec.control
    threshold = math.log(1.0 / spec.alpha)
    c_stats = stats.get(control, {})
    c_conv = float(c_stats.get("converted", 0))
    c_miss = float(c_stats.get("miss", 0))
    c_n = c_conv + c_miss
    c_rate = (c_conv / c_n) if c_n else None
    c_p99 = c_stats.get("p99_s")

    variants: Dict[str, Dict[str, Any]] = {}
    variants[control] = {
        "converted": c_conv,
        "miss": c_miss,
        "attributed": c_n,
        "requests": float(c_stats.get("requests", 0)),
        "hit_rate": c_rate,
        "p99_s": c_p99,
        "log_lambda": 0.0,
        "significant": False,
        "better": False,
        "guard_ok": True,
    }

    contenders = []
    all_lost = True
    for vid in spec.variants[1:]:
        v_stats = stats.get(vid, {})
        conv = float(v_stats.get("converted", 0))
        miss = float(v_stats.get("miss", 0))
        n = conv + miss
        rate = (conv / n) if n else None
        p99 = v_stats.get("p99_s")
        enough = n >= spec.min_samples and c_n >= spec.min_samples
        log_lambda = (
            msprt_log_lambda(c_conv, c_n, conv, n, spec.tau) if enough else 0.0
        )
        significant = enough and log_lambda >= threshold
        better = (
            rate is not None and c_rate is not None and rate > c_rate
        )
        guard = _guard_ok(spec, p99, c_p99)
        variants[vid] = {
            "converted": conv,
            "miss": miss,
            "attributed": n,
            "requests": float(v_stats.get("requests", 0)),
            "hit_rate": rate,
            "p99_s": p99,
            "log_lambda": log_lambda,
            "significant": significant,
            "better": better,
            "guard_ok": guard,
        }
        if significant and better and guard:
            contenders.append((rate, vid))
        if not (significant and not better):
            all_lost = False

    report: Dict[str, Any] = {
        "experiment": spec.name,
        "control": control,
        "primary_metric": spec.primary_metric,
        "alpha": spec.alpha,
        "threshold_log_lambda": threshold,
        "elapsed_s": elapsed_s,
        "status": "running",
        "winner": None,
        "action": None,
        "variants": variants,
    }
    if contenders:
        report["status"] = "decided"
        report["winner"] = max(contenders)[1]
        report["action"] = f"promote:{report['winner']}"
    elif all_lost:
        # Every candidate significantly underperforms: control wins.
        report["status"] = "decided"
        report["winner"] = control
        report["action"] = "keep-control"
    elif elapsed_s is not None and elapsed_s >= spec.horizon_s:
        report["status"] = "horizon"
        report["action"] = spec.on_inconclusive
    return report


# --- server-side active state (held by QueryAPI; routing is the pure
# allocation above applied to the request's user key) ---


class ActiveExperiment:
    """Spec + the per-variant DeployedEngines, as bound into a serving
    ``QueryAPI``.  Routing is stateless; the only state here is the
    engine map itself."""

    def __init__(self, spec: ExperimentSpec, engines: Dict[str, Any]):
        missing = set(spec.variants) - set(engines)
        if missing:
            raise ValueError(f"experiment missing engines for {sorted(missing)}")
        self.spec = spec
        self.engines = dict(engines)
        self.started_s = time.time()

    def route(self, query_json: Any) -> Tuple[str, Any]:
        vid = allocate(
            self.spec, user_key_from_query(query_json, self.spec.user_field)
        )
        return vid, self.engines[vid]

    def status(self) -> Dict[str, Any]:
        return {
            "spec": self.spec.to_json(),
            "startedS": self.started_s,
            "elapsedS": max(0.0, time.time() - self.started_s),
            "variants": list(self.spec.variants),
        }


# --- local (in-process) stats source ---


def local_variant_stats(spec: ExperimentSpec) -> Dict[str, Dict[str, Any]]:
    """Per-variant cumulative counts read from the process-global
    registry — the single-box evaluation source (the fleet-shaped source
    is the collector's federated ring)."""
    from predictionio_tpu.utils.metrics import (
        get_registry,
        histogram_quantile_from_samples,
        parse_exposition,
        sample_family_name,
        sample_label_value,
    )

    samples = parse_exposition(get_registry().render())
    stats: Dict[str, Dict[str, Any]] = {
        vid: {"converted": 0.0, "miss": 0.0, "requests": 0.0, "p99_s": None}
        for vid in spec.variants
    }
    by_variant_latency: Dict[str, Dict[str, float]] = {}
    for key, value in samples.items():
        family = sample_family_name(key)
        if family == "pio_online_attributed_total":
            vid = sample_label_value(key, "version")
            outcome = sample_label_value(key, "outcome")
            if vid in stats and outcome in ("converted", "miss"):
                stats[vid][outcome] += value
        elif family == "pio_serving_requests_total":
            vid = sample_label_value(key, "version")
            if vid in stats:
                stats[vid]["requests"] += value
        elif family == "pio_serving_latency_seconds_bucket":
            vid = sample_label_value(key, "version")
            if vid in stats:
                by_variant_latency.setdefault(vid, {})[key] = value
    for vid, lat in by_variant_latency.items():
        stats[vid]["p99_s"] = histogram_quantile_from_samples(
            lat, "pio_serving_latency_seconds", 0.99
        )
    return stats


def _delta_stats(
    now: Dict[str, Dict[str, Any]], base: Dict[str, Dict[str, Any]]
) -> Dict[str, Dict[str, Any]]:
    out: Dict[str, Dict[str, Any]] = {}
    for vid, cur in now.items():
        prev = base.get(vid, {})
        out[vid] = {
            "converted": max(
                0.0, cur.get("converted", 0.0) - prev.get("converted", 0.0)
            ),
            "miss": max(0.0, cur.get("miss", 0.0) - prev.get("miss", 0.0)),
            "requests": max(
                0.0, cur.get("requests", 0.0) - prev.get("requests", 0.0)
            ),
            "p99_s": cur.get("p99_s"),
        }
    return out


# --- runner: evaluation loop + verdict execution ---


class ExperimentRunner:
    """Drives one experiment end to end on an in-process server: start
    (all arms warm), peek the sequential test each step, and on a
    verdict execute it — winner promoted through the gated promotion
    pipeline, losers drained/released via the retained-LRU path.

    ``collector`` (a :class:`predictionio_tpu.utils.telemetry.Collector`)
    supplies the fleet-shaped stats when given; otherwise counts come
    from the process-global registry.
    """

    def __init__(
        self,
        server,
        storage,
        spec: ExperimentSpec,
        collector=None,
        pipeline=None,
        promotion_config=None,
        poll_s: float = 1.0,
        clock: Callable[[], float] = time.time,
    ):
        self.server = server
        self.storage = storage
        self.spec = spec
        self.collector = collector
        self.poll_s = poll_s
        self._clock = clock
        self._started_s: Optional[float] = None
        self._baseline: Dict[str, Dict[str, Any]] = {}
        if pipeline is None:
            from predictionio_tpu.workflow.promotion import (
                InProcessTarget,
                PromotionPipeline,
            )

            pipeline = PromotionPipeline(
                InProcessTarget(server), promotion_config, storage
            )
        self.pipeline = pipeline
        self.final_report: Optional[Dict[str, Any]] = None

    def start(self) -> Dict[str, Any]:
        status = self.server.start_experiment(self.spec)
        self._started_s = self._clock()
        if self.collector is not None:
            self.collector.register_experiment(self.spec)
        else:
            self._baseline = local_variant_stats(self.spec)
        return status

    def peek(self) -> Dict[str, Any]:
        """Evaluate the sequential test once (no side effects)."""
        elapsed = (
            max(0.0, self._clock() - self._started_s)
            if self._started_s is not None
            else 0.0
        )
        if self.collector is not None:
            report = self.collector.experiment_report(self.spec.name)
            if report is not None:
                return report
            return evaluate_sequential(self.spec, {}, elapsed_s=elapsed)
        stats = _delta_stats(local_variant_stats(self.spec), self._baseline)
        return evaluate_sequential(self.spec, stats, elapsed_s=elapsed)

    def step(self) -> Optional[Dict[str, Any]]:
        """One peek; executes the verdict when the test has decided (or
        the horizon passed).  Returns the final report then, else None."""
        report = self.peek()
        if report.get("status") == "running":
            return None
        return self._finish(report)

    def run(
        self,
        stop_event=None,
        max_steps: Optional[int] = None,
    ) -> Dict[str, Any]:
        steps = 0
        while True:
            final = self.step()
            if final is not None:
                return final
            steps += 1
            if max_steps is not None and steps >= max_steps:
                return self._finish(self.peek())
            if stop_event is not None and stop_event.wait(self.poll_s):
                return self._finish(self.peek())
            if stop_event is None:
                time.sleep(self.poll_s)

    def _finish(self, report: Dict[str, Any]) -> Dict[str, Any]:
        """Execute the verdict: stop allocation, drain losers, promote
        the winner through the gated pipeline (shadow + observation
        window intact — an experiment win is evidence, not a bypass)."""
        if self.final_report is not None:
            return self.final_report
        winner = report.get("winner")
        if winner is None:
            # Inconclusive at horizon (or forced stop).
            winner = (
                self.spec.control
                if self.spec.on_inconclusive == "keep-control"
                else None
            )
        live = self.server.api.deployed.engine_instance.id
        self.server.stop_experiment(winner=winner)
        if self.collector is not None:
            self.collector.remove_experiment(self.spec.name)
        promotion = None
        if winner is not None and winner != live:
            promotion = self.pipeline.promote(winner)
        report = dict(report, resolved_winner=winner, promotion=promotion)
        self.final_report = report
        return report
