"""Continuous (delta) training: poll → delta-fold → warm-train → checkpoint.

The production story for "models that follow live traffic" (ROADMAP):
instead of a cron'd full retrain whose cost scales with the STORE, a
long-running loop retrains at a fixed cadence with cost proportional to
the DELTA — each round the streaming trainer's pack cache folds only the
events committed since the previous round into the cached wire and
warm-starts the factors from the previous model (ops/streaming, the ALX
/ GPU-MF warm-start observation). Every round persists a full engine
instance + model blob through CoreWorkflow.run_train, so the newest
COMPLETED instance is always deployable — the checkpoint step the
zero-downtime hot-swap item builds on.

Idle rounds are CHEAP, not just fast: before training, the loop polls
the datasource app's store fingerprint (the same aggregate the pack
cache keys on) and skips the round entirely when nothing changed —
polling a quiet 20M-event store costs a few SQL aggregates, not a
train. When the datasource's shape is unknown (no ``app_name`` param),
the loop trains every round and the pack cache still keeps unchanged
rounds to a cached-wire retrain.

The loop is shutdown-aware by construction: it parks on
``stop_event.wait(interval)`` between rounds, so a SIGTERM (wired by
``pio train --continuous``) ends it at the next boundary — the loop
class tests/test_lint.py's while-True lint exists to police.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import logging
import threading
import time
from typing import Callable, Dict, Optional

from predictionio_tpu.utils import health as _health
from predictionio_tpu.utils import metrics as _metrics

logger = logging.getLogger(__name__)

# a trained round parks inside hb.busy() for the whole train; the
# watchdog deadline must exceed any healthy round. Tests (and operators
# with known round budgets) tighten hb.deadline_s directly.
ROUND_DEADLINE_S = 3600.0

_ROUND_OUTCOMES = ("trained", "skipped", "failed")


def _round_counter() -> "_metrics.Counter":
    return _metrics.get_registry().counter(
        "pio_continuous_rounds_total",
        "Continuous-training loop rounds by outcome",
        labels=("outcome",),
    )


def _round_seconds() -> "_metrics.Histogram":
    return _metrics.get_registry().histogram(
        "pio_continuous_round_seconds",
        "Wall clock of one continuous-training round (trained rounds)",
        buckets=_metrics.LATENCY_BUCKETS_S,
    )


def continuous_round_stats() -> Dict[str, int]:
    """Lifetime trained/skipped/failed round counts from the registry
    (status.json's ``continuousRounds`` block)."""
    c = _round_counter()
    return {
        k: int(c.labels(outcome=k).value) for k in _ROUND_OUTCOMES
    }


@dataclasses.dataclass
class RoundReport:
    """What one loop round did — handed to ``on_round`` for CLI/bench
    reporting (per-round delta size and wall clock; the PhaseTimer
    summary carries the full phase split and cache counters)."""

    round: int
    skipped: bool  # fingerprint unchanged: no train this round
    wall_s: float
    instance_id: Optional[str] = None
    pack_cache: Optional[str] = None  # hit/miss/fold for this round
    delta_events: Optional[int] = None
    timer_summary: str = ""
    # convergence telemetry from the fused device loop (ops/als.py):
    # sweep count and the final sweep's factor-delta RMS per side
    sweeps: Optional[int] = None
    final_factor_delta: Optional[str] = None
    # implicit-feedback training objective (Hu-Koren-Volinsky loss via
    # the Gramian trick) at the round's final sweep — the loss headline
    # trended alongside hit-rate by the quality/promotion tier. None in
    # explicit mode or when telemetry is off.
    objective: Optional[str] = None
    # device-resident pack outcome for this round (ops/streaming.py):
    # "scatter" when the delta was scattered onto the resident HBM
    # pack, "fallback" when a resident pack had to be demoted to the
    # host fold, "cold" for a from-scratch round. None when residency
    # is disabled or the round was skipped.
    resident: Optional[str] = None
    # shadow-scoring verdict (workflow/quality.py shadow_score): the
    # candidate instance scored against the previous round's (live)
    # instance on the captured query sample — jaccard/displacement/
    # score-delta plus the 'comparable'/'diverged' verdict the
    # promotion pipeline consumes as its refuse-swap gate. None when
    # shadow scoring is disabled, no previous instance exists yet, or
    # the capture ring is empty.
    shadow: Optional[Dict] = None
    # promotion-pipeline report (workflow/promotion.py): outcome
    # (promoted/refused/failed/rolled_back), per-stage timings, and the
    # version the serving target ended up on. None when no pipeline is
    # wired into the loop.
    promotion: Optional[Dict] = None


def poll_fingerprint(engine_params, storage) -> Optional[tuple]:
    """The datasource app's cheap store fingerprint, or None when the
    datasource params don't name an app (loop then trains every round).
    Uses the SAME fingerprint the pack cache keys on, so 'unchanged
    here' exactly predicts a cache hit there."""
    try:
        ds = engine_params.data_source_params
        if isinstance(ds, tuple):  # (name, params)
            ds = ds[1]
        app_name = getattr(ds, "app_name", None)
        if not app_name:
            return None
        from predictionio_tpu.data.store import app_name_to_id

        app_id, channel_id = app_name_to_id(
            app_name, getattr(ds, "channel_name", None), storage
        )
        return storage.get_p_events().store_fingerprint(app_id, channel_id)
    except Exception:
        logger.debug("continuous: fingerprint poll failed", exc_info=True)
        return None


def _shadow_round(
    engine, storage, live_instance_id, candidate_instance_id,
    shadow_queries: int, shadow_min_jaccard: float,
) -> Optional[Dict]:
    """Shadow-score one trained round; never fails the loop (a broken
    shadow comparison is an observability gap, not a training error)."""
    from predictionio_tpu.workflow import quality as _quality

    records = _quality.get_capture().sample(shadow_queries)
    if not records:
        return None
    try:
        shadow = _quality.shadow_score(
            engine, storage, live_instance_id, candidate_instance_id,
            records, min_jaccard=shadow_min_jaccard,
        )
        logger.info(
            "shadow round: %s vs %s on %d captured queries — %s "
            "(jaccard %.4f)",
            candidate_instance_id, live_instance_id, shadow["queries"],
            shadow["verdict"], shadow["jaccard_mean"],
        )
        return shadow
    except Exception:
        logger.exception("shadow scoring failed")
        return None


def continuous_train(
    engine,
    engine_params,
    instance_template,
    *,
    workflow_params=None,
    storage=None,
    mesh=None,
    interval_s: float = 10.0,
    stop_event: Optional[threading.Event] = None,
    max_rounds: Optional[int] = None,
    on_round: Optional[Callable[[RoundReport], None]] = None,
    shadow_queries: int = 0,
    shadow_min_jaccard: float = 0.5,
    promotion=None,
    resident: bool = True,
) -> int:
    """Run the poll→delta-fold→warm-train→checkpoint loop until
    ``stop_event`` is set (or ``max_rounds`` rounds ran — tests/bench).
    Returns the number of rounds executed (trained or skipped).

    ``instance_template`` is re-stamped per round, so every trained
    round records its own engine instance + model blob.

    ``mesh`` defaults to a 1-DEVICE mesh: the delta fold and warm start
    live in the single-device streaming pipeline (algorithms collapse a
    trivial mesh onto it), and a continuous retrain at delta cost never
    needs the full slice — mesh-parallel retraining is the ROADMAP's
    ALX-style sharded item. Pass an explicit mesh to override.

    ``shadow_queries`` > 0 shadow-scores every trained round: the fresh
    candidate instance is served against the PREVIOUS round's instance
    on the newest ``shadow_queries`` records of the process-global
    prediction capture (workflow/quality.py), and the verdict —
    ``comparable`` when the mean jaccard clears ``shadow_min_jaccard``
    — lands in ``RoundReport.shadow`` and the ``pio_shadow_*``
    families. This runs on the training loop only, never the serving
    path.

    ``promotion`` (a workflow/promotion.PromotionPipeline) closes the
    retrain→serve loop: every trained round's candidate runs the full
    gated swap pipeline — the shadow verdict is its HARD gate (diverged
    ⇒ the swap is refused and the fleet keeps serving the live
    instance), then prepare/warm off the hot path → atomic swap →
    bounded drain → post-swap observation with automatic rollback. The
    report lands in ``RoundReport.promotion``; the loop's notion of the
    LIVE instance (the shadow baseline) then follows what the serving
    target actually serves, so a refused or rolled-back round keeps
    shadow-scoring future candidates against the version still taking
    traffic.

    ``resident`` keeps the packed wire + factor state in device memory
    between rounds (ops/streaming.ResidentPack), so a steady-state
    round uploads only the delta rows. The loop OWNS the handles: they
    are released (and the byte-identical host wire restored) when the
    loop exits — shutdown, max_rounds, or an error — and the
    streaming trainer itself demotes a pack on any fallback-to-cold
    round, so the ``train-pack`` device-ledger component reads zero
    whenever no loop is live. Residency is scoped to the loop: the
    previous process-wide setting is restored on exit."""
    from predictionio_tpu.ops import streaming as _streaming

    if mesh is None:
        import jax

        from predictionio_tpu.parallel import make_mesh
        from predictionio_tpu.utils.compilation_cache import (
            ensure_compilation_cache,
        )

        ensure_compilation_cache()
        mesh = make_mesh({"data": 1}, jax.devices()[:1])
    stop = stop_event if stop_event is not None else threading.Event()
    # the "live" reference for shadow scoring: the previous trained
    # round's instance (what a deployed server would be serving now).
    # With a promotion pipeline wired in, seed it from what the serving
    # target ACTUALLY serves, so round 1's candidate already shadows
    # against live traffic's model.
    live_instance_id: Optional[str] = None
    if promotion is not None:
        try:
            live_instance_id = promotion.target.current_version()
        except Exception:
            logger.warning(
                "could not read the serving target's current version; "
                "shadow gating starts at the first trained round",
                exc_info=True,
            )
    # watchdog: a round that wedges (a hung scan, a stuck device call)
    # flips every in-process server's /readyz to 503 once it overruns
    # the deadline — the signal the hot-swap/fleet tier routes on
    hb = _health.heartbeat("continuous-train", deadline_s=ROUND_DEADLINE_S)
    prev_resident: Optional[bool] = None
    if resident:
        prev_resident = _streaming.set_resident_training(True)
    try:
        rounds = _continuous_loop(
            engine, engine_params, instance_template, workflow_params,
            storage, mesh, interval_s, stop, max_rounds, on_round,
            shadow_queries, shadow_min_jaccard, promotion,
            live_instance_id, hb,
        )
    finally:
        if resident:
            released = _streaming.release_resident_packs()
            if released:
                logger.info(
                    "continuous: released %d resident pack(s) on exit",
                    released,
                )
            _streaming.set_resident_training(bool(prev_resident))
    return rounds


def _continuous_loop(
    engine, engine_params, instance_template, workflow_params, storage,
    mesh, interval_s, stop, max_rounds, on_round, shadow_queries,
    shadow_min_jaccard, promotion, live_instance_id, hb,
) -> int:
    """The poll→train→report loop body of :func:`continuous_train`,
    split out so the resident-pack lifecycle wraps it in one
    try/finally."""
    from predictionio_tpu.workflow.context import workflow_context
    from predictionio_tpu.workflow.core_workflow import CoreWorkflow

    rounds = 0
    last_fp: Optional[tuple] = None
    trained_once = False
    while not stop.is_set():
        t0 = time.perf_counter()
        ctx = workflow_context(
            mode="training",
            batch=getattr(instance_template, "batch", ""),
            storage=storage,
            mesh=mesh,
        )
        fp = poll_fingerprint(engine_params, ctx.storage)
        if trained_once and fp is not None and fp == last_fp:
            _round_counter().labels(outcome="skipped").inc()
            report = RoundReport(
                round=rounds + 1, skipped=True,
                wall_s=time.perf_counter() - t0,
            )
            logger.info(
                "continuous round %d: store unchanged, skipped",
                report.round,
            )
        else:
            now = _dt.datetime.now(_dt.timezone.utc)
            instance = dataclasses.replace(
                instance_template, id="", start_time=now, end_time=now
            )
            try:
                with hb.busy():
                    instance_id = CoreWorkflow.run_train(
                        engine, engine_params, instance,
                        ctx=ctx, workflow_params=workflow_params,
                    )
            except BaseException:
                _round_counter().labels(outcome="failed").inc()
                raise
            _round_counter().labels(outcome="trained").inc()
            _round_seconds().observe(time.perf_counter() - t0)
            trained_once = True
            # the PRE-train fingerprint labels the round: events landing
            # during the train make the next poll differ, so they are
            # picked up next round, never silently skipped
            last_fp = fp
            notes = getattr(ctx.timer, "notes", {})
            report = RoundReport(
                round=rounds + 1, skipped=False,
                wall_s=time.perf_counter() - t0,
                instance_id=instance_id,
                pack_cache=notes.get("pack_cache"),
                delta_events=notes.get("delta_events"),
                timer_summary=ctx.timer.summary(),
                sweeps=notes.get("sweeps"),
                final_factor_delta=notes.get("final_factor_delta"),
                objective=notes.get("objective"),
                resident=notes.get("resident"),
            )
            if shadow_queries > 0 and live_instance_id and instance_id:
                report.shadow = _shadow_round(
                    engine, ctx.storage, live_instance_id, instance_id,
                    shadow_queries, shadow_min_jaccard,
                )
            if promotion is not None and instance_id:
                # the gated swap pipeline; promote() never raises on an
                # ordinary failure (the fleet keeps serving a consistent
                # version), so the loop survives a refused/failed round
                # and retries with the NEXT trained candidate
                report.promotion = promotion.promote(
                    instance_id, shadow=report.shadow
                )
                served = report.promotion.get("serving")
                if served:
                    live_instance_id = served
            elif instance_id:
                live_instance_id = instance_id
            logger.info(
                "continuous round %d: %s in %.3fs (%s%s%s%s)",
                report.round, instance_id, report.wall_s,
                report.pack_cache or "n/a",
                (
                    f", {report.delta_events} delta events"
                    if report.delta_events is not None
                    else ""
                ),
                (
                    f", {report.sweeps} sweeps, final delta "
                    f"{report.final_factor_delta}"
                    if report.sweeps is not None
                    else ""
                ),
                (
                    f", objective {report.objective}"
                    if report.objective is not None
                    else ""
                ),
            )
        rounds += 1
        if on_round is not None:
            on_round(report)
        if max_rounds is not None and rounds >= max_rounds:
            break
        if stop.wait(interval_s):
            break
    return rounds
