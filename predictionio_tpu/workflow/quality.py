"""Online model-quality observability: attribution, capture, replay, shadow.

PRs 6–7 made the *system* observable; this module makes the *model*
observable in production. Four cooperating pieces, all keyed by the two
identifiers the serving tier already emits:

- the **model version** — the engine instance id of the persisted round
  a prediction was served from (stamped on every response and on every
  feedback ``predict`` event as ``engineInstanceId``);
- the **prId** — the 64-char correlation id the feedback loop mints per
  served prediction (reference CreateServer.scala:525) so subsequent
  user events can be attributed back to the prediction that caused them.

1. :class:`AttributionTable` — a bounded, TTL'd table of recently
   served predictions (prId → version, served item ids), fed by the
   event server's commit hook from the feedback ``predict`` events
   themselves (the join key rides the ordinary event stream, so it
   works across processes with zero extra plumbing). Events arriving
   with a ``prId`` join against it and emit
   ``pio_online_attributed_total{version,outcome}`` plus
   rank-of-conversion and time-to-conversion histograms — real
   CTR-style quality per model version, computed on the ingest path.
2. :class:`PredictionCapture` — a sampled bounded ring of served
   predictions (query, result item ids/scores, version, trace id),
   dumped at the engine server's gated ``GET /debug/predictions.json``
   and persistable to a capture file.
3. :func:`replay_capture` — re-run a capture against any persisted
   model instance and report divergence (jaccard@n, rank displacement,
   score delta). A self-replay against the instance that produced the
   capture reports exactly zero divergence — the deterministic
   regression oracle for model swaps.
4. :func:`shadow_score` — score a freshly trained candidate instance
   against the live instance on the captured query sample (the
   continuous-training loop calls it per round), recording
   ``pio_shadow_*`` families and a per-round verdict — the refuse-swap
   signal the zero-downtime deployment pipeline consumes.

Like utils/tracing.py, this module is a sanctioned home for bounded
module-level observability state (the process-global capture ring and
attribution table); every counter lives in the metrics registry.
"""

from __future__ import annotations

import collections
import hashlib
import json
import logging
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from predictionio_tpu.utils import metrics as _metrics

logger = logging.getLogger(__name__)

__all__ = [
    "AttributionTable",
    "PredictionCapture",
    "attribution_observer",
    "compare_topn",
    "extract_items",
    "get_attribution",
    "get_capture",
    "load_capture",
    "replay_capture",
    "save_capture",
    "shadow_score",
]

# response/result keys the serving tier injects after the model ran —
# stripped before the generic whole-result comparison so a replayed
# result (no prId minted, same model) still matches its capture
_VOLATILE_RESULT_KEYS = ("prId", "modelVersion", "experiment", "variant")

ATTRIBUTION_OUTCOMES = ("converted", "miss", "unknown")


def extract_items(result_json: Any) -> Tuple[Tuple[str, ...], Tuple[float, ...]]:
    """The ordered (item ids, scores) of a served prediction's JSON.

    Engines speaking the reference wire format (``itemScores`` — the
    recommendation/similarproduct/ecommerce templates) yield their real
    ranked item lists. Any other result shape degrades to ONE pseudo
    item — a digest of the canonical result JSON — so identity
    comparisons (jaccard 1.0 vs 0.0) still work for arbitrary engines.
    """
    if isinstance(result_json, dict):
        scores = result_json.get("itemScores")
        if isinstance(scores, list) and all(
            isinstance(s, dict) and "item" in s for s in scores
        ):
            return (
                tuple(str(s["item"]) for s in scores),
                tuple(float(s.get("score", 0.0)) for s in scores),
            )
        result_json = {
            k: v
            for k, v in result_json.items()
            if k not in _VOLATILE_RESULT_KEYS
        }
    blob = json.dumps(result_json, sort_keys=True, default=str)
    digest = hashlib.sha1(blob.encode("utf-8")).hexdigest()
    return (digest,), (0.0,)


def compare_topn(
    a_items: Sequence[str],
    a_scores: Sequence[float],
    b_items: Sequence[str],
    b_scores: Sequence[float],
) -> Dict[str, float]:
    """Divergence between two ranked result lists.

    - ``jaccard``: set overlap of the served ids (1.0 when both empty);
    - ``rank_displacement``: mean |rank_a − rank_b| over the common ids
      (0.0 when nothing is common — jaccard carries that signal);
    - ``score_delta``: mean |score_a − score_b| over the common ids.
    """
    sa, sb = set(a_items), set(b_items)
    union = sa | sb
    common = sa & sb
    jaccard = (len(common) / len(union)) if union else 1.0
    pos_a = {item: i for i, item in enumerate(a_items)}
    pos_b = {item: i for i, item in enumerate(b_items)}
    score_a = dict(zip(a_items, a_scores))
    score_b = dict(zip(b_items, b_scores))
    if common:
        displacement = sum(
            abs(pos_a[i] - pos_b[i]) for i in common
        ) / len(common)
        score_delta = sum(
            abs(score_a.get(i, 0.0) - score_b.get(i, 0.0)) for i in common
        ) / len(common)
    else:
        displacement = 0.0
        score_delta = 0.0
    return {
        "jaccard": jaccard,
        "rank_displacement": displacement,
        "score_delta": score_delta,
    }


# --- attribution: the prId → served-prediction join on the ingest path ---


def _attributed_counter() -> "_metrics.Counter":
    return _metrics.get_registry().counter(
        "pio_online_attributed_total",
        "Ingested events joined against recently served predictions, "
        "by model version and outcome (converted = the event's target "
        "item was in the served list)",
        labels=("version", "outcome"),
    )


def _conversion_rank_hist() -> "_metrics.Histogram":
    return _metrics.get_registry().histogram(
        "pio_online_conversion_rank",
        "1-based rank of the converted item within its served list",
        labels=("version",),
        buckets=_metrics.BATCH_SIZE_BUCKETS,
    )


def _time_to_conversion_hist() -> "_metrics.Histogram":
    return _metrics.get_registry().histogram(
        "pio_online_time_to_conversion_seconds",
        "Serve-to-feedback-event delay for converted predictions",
        labels=("version",),
        buckets=_metrics.LATENCY_BUCKETS_S,
    )


class AttributionTable:
    """Bounded TTL'd prId → (version, served item ids, t) table.

    Registered from feedback ``predict`` events (entityType ``pio_pr``,
    entityId = the served prId — reference CreateServer.scala:509-579);
    queried by every ingested event that carries a ``prId``. Both sides
    run on the event server's ingest path, so each operation is one
    lock + dict op — the overhead is hard-gated <2% of batch-ingest
    throughput by ``bench.py --only quality``.
    """

    def __init__(self, ttl_s: float = 900.0, max_entries: int = 65536):
        self.ttl_s = float(ttl_s)
        self.max_entries = int(max_entries)
        self._entries: "collections.OrderedDict[str, tuple]" = (
            collections.OrderedDict()
        )
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def register(
        self,
        pr_id: str,
        version: str,
        items: Sequence[str],
        t: Optional[float] = None,
    ) -> None:
        now = time.time() if t is None else t
        with self._lock:
            self._entries.pop(pr_id, None)
            self._entries[pr_id] = (version, tuple(items), now)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)

    def register_from_event(self, event) -> None:
        """Register a feedback ``predict`` event: entityId is the served
        prId, ``engineInstanceId`` the model version, ``prediction`` the
        served result JSON."""
        props = event.properties
        version = str(props.get_opt("engineInstanceId") or "unknown")
        items, _ = extract_items(props.get_opt("prediction"))
        self.register(event.entity_id, version, items)

    def observe(self, event, now: Optional[float] = None) -> Optional[str]:
        """Join one prId-carrying event; returns the outcome recorded
        (``converted`` / ``miss`` / ``unknown``), or None when the
        event carries no prId."""
        pr_id = event.pr_id
        if not pr_id:
            return None
        now = time.time() if now is None else now
        with self._lock:
            entry = self._entries.get(pr_id)
            if entry is not None and now - entry[2] > self.ttl_s:
                self._entries.pop(pr_id, None)
                entry = None
        if entry is None:
            _attributed_counter().labels(
                version="unknown", outcome="unknown"
            ).inc()
            return "unknown"
        version, items, t_served = entry
        target = event.target_entity_id
        rank0 = items.index(target) if target in items else -1
        outcome = "converted" if rank0 >= 0 else "miss"
        _attributed_counter().labels(version=version, outcome=outcome).inc()
        if rank0 >= 0:
            _conversion_rank_hist().labels(version=version).observe(rank0 + 1)
            _time_to_conversion_hist().labels(version=version).observe(
                max(0.0, now - t_served)
            )
        return outcome

    def stats(self) -> Dict[str, Any]:
        """Registry-backed attribution summary (status.json / tests):
        per-version outcome counts plus the derived hit rate
        (converted / (converted + miss))."""
        per_version: Dict[str, Dict[str, int]] = {}
        for (version, outcome), child in _attributed_counter().children():
            per_version.setdefault(version, {})[outcome] = int(child.value)
        out: Dict[str, Any] = {"tracked": len(self), "versions": {}}
        for version, counts in per_version.items():
            converted = counts.get("converted", 0)
            missed = counts.get("miss", 0)
            denom = converted + missed
            out["versions"][version] = {
                **counts,
                "hitRate": (converted / denom) if denom else 0.0,
            }
        return out


def attribution_observer(table: Optional[AttributionTable] = None):
    """The event-server commit-hook observer (EventAPI registers it when
    ``EventServerConfig.attribution`` is on): feedback ``predict``
    events populate the table, prId-carrying events join against it.
    The hook point (``EventAPI.add_commit_observer``) is deliberately
    generic — the per-user-cache tier's change notifications (ROADMAP)
    ride the same hook."""
    table = table if table is not None else get_attribution()

    def observe(app_id, channel_id, events) -> None:
        for e in events:
            if e.entity_type == "pio_pr" and e.event == "predict":
                table.register_from_event(e)
            elif e.pr_id:
                table.observe(e)

    return observe


# --- prediction capture: the sampled serving-record ring ---


def _captured_counter() -> "_metrics.Counter":
    return _metrics.get_registry().counter(
        "pio_predictions_captured_total",
        "Served predictions recorded into the capture ring, by version",
        labels=("version",),
    )


class PredictionCapture:
    """Bounded ring of served-prediction records. Each record is a JSON
    dict — the capture *file* format is exactly these records, one per
    line (or a ``{"predictions": [...]}`` dump / plain JSON array, the
    shapes ``load_capture`` accepts):

    ``{"prId", "version", "query", "result", "items", "scores",
    "traceId", "tMs", "latencyMs"}``

    Records served under an experiment additionally carry
    ``{"experiment", "variant"}`` (variant = the serving arm's engine
    instance id), so a capture taken during an A/B run can be replayed
    per arm (``pio replay --serving-variant``).

    ``items``/``scores`` are extracted at capture time so the replay
    comparison never depends on how an engine's result JSON evolves.
    """

    def __init__(self, capacity: int = 1024):
        self._records: "collections.deque" = collections.deque(
            maxlen=int(capacity)
        )
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def clear(self) -> None:
        with self._lock:
            self._records.clear()

    def record(
        self,
        version: str,
        query_json: Any,
        result_json: Any,
        pr_id: Optional[str] = None,
        trace_id: Optional[str] = None,
        latency_s: float = 0.0,
        experiment: Optional[str] = None,
        variant: Optional[str] = None,
    ) -> dict:
        items, scores = extract_items(result_json)
        entry = {
            "prId": pr_id,
            "version": version,
            "query": query_json,
            "result": result_json,
            "items": list(items),
            "scores": [round(s, 8) for s in scores],
            "traceId": trace_id,
            "tMs": round(time.time() * 1000.0, 3),
            "latencyMs": round(latency_s * 1000.0, 3),
        }
        if experiment is not None:
            entry["experiment"] = experiment
            entry["variant"] = variant if variant is not None else version
        with self._lock:
            self._records.append(entry)
        _captured_counter().labels(version=version).inc()
        return entry

    def dump(
        self,
        limit: Optional[int] = None,
        version: Optional[str] = None,
        variant: Optional[str] = None,
    ) -> List[dict]:
        with self._lock:
            records = list(self._records)
        if version:
            records = [r for r in records if r.get("version") == version]
        if variant:
            records = [r for r in records if r.get("variant") == variant]
        if limit is not None:
            records = records[-int(limit):]
        return records

    def sample(self, n: int) -> List[dict]:
        """The newest ``n`` records — the shadow-scoring query sample."""
        return self.dump(limit=n)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            records = list(self._records)
        versions: Dict[str, int] = {}
        for r in records:
            versions[r.get("version", "unknown")] = (
                versions.get(r.get("version", "unknown"), 0) + 1
            )
        return {"records": len(records), "versions": versions}


def save_capture(path: str, records: Iterable[dict]) -> int:
    """Persist capture records as JSON lines; returns the count."""
    n = 0
    with open(path, "w", encoding="utf-8") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
            n += 1
    return n


def load_capture(path: str) -> List[dict]:
    """Load a capture file: JSON lines (``save_capture``), a JSON array,
    or a ``{"predictions": [...]}`` object (a saved
    ``/debug/predictions.json`` response) all work."""
    with open(path, "r", encoding="utf-8") as f:
        text = f.read().strip()
    if not text:
        return []
    if text[0] in "[{":
        try:
            obj = json.loads(text)
        except json.JSONDecodeError:
            obj = None
        if isinstance(obj, dict) and isinstance(
            obj.get("predictions"), list
        ):
            return obj["predictions"]
        if isinstance(obj, list):
            return obj
    return [json.loads(line) for line in text.splitlines() if line.strip()]


# --- replay: the deterministic divergence oracle ---


def _serve_records(deployed, records: List[dict], batch: int = 64):
    """Re-serve each record's query through ``deployed`` (its own
    micro-batch path, ``serve_batch``) and yield per-record
    (items, scores) extracted from the fresh result JSON."""
    algo = deployed.algorithms[0]
    for start in range(0, len(records), max(1, batch)):
        chunk = records[start:start + max(1, batch)]
        queries = [algo.query_from_json(r["query"]) for r in chunk]
        results = deployed.serve_batch(queries)
        for prediction in results:
            yield extract_items(algo.result_to_json(prediction))


def replay_capture(
    records: List[dict],
    deployed,
    batch: int = 64,
    score_tol: float = 1e-5,
) -> Dict[str, Any]:
    """Re-run a capture against ``deployed`` and report divergence
    against the recorded results. A self-replay (same instance the
    capture was recorded from) must report jaccard 1.0 / displacement 0
    — asserted by tests and the bench smoke."""
    n = 0
    diverged = 0
    jaccards: List[float] = []
    displacements: List[float] = []
    score_deltas: List[float] = []
    worst: Optional[dict] = None
    for record, (items, scores) in zip(
        records, _serve_records(deployed, records, batch=batch)
    ):
        cmp = compare_topn(
            record.get("items") or (),
            record.get("scores") or (),
            items,
            scores,
        )
        n += 1
        jaccards.append(cmp["jaccard"])
        displacements.append(cmp["rank_displacement"])
        score_deltas.append(cmp["score_delta"])
        is_diverged = (
            cmp["jaccard"] < 1.0
            or cmp["rank_displacement"] > 0
            or cmp["score_delta"] > score_tol
        )
        if is_diverged:
            diverged += 1
            if worst is None or cmp["jaccard"] < worst["jaccard"]:
                worst = {**cmp, "query": record.get("query")}
    report: Dict[str, Any] = {
        "queries": n,
        "diverged": diverged,
        "targetVersion": deployed.engine_instance.id,
        "jaccard_mean": (sum(jaccards) / n) if n else 1.0,
        "jaccard_min": min(jaccards) if jaccards else 1.0,
        "rank_displacement_mean": (
            (sum(displacements) / n) if n else 0.0
        ),
        "rank_displacement_max": max(displacements) if displacements else 0.0,
        "score_delta_mean": (sum(score_deltas) / n) if n else 0.0,
    }
    if worst is not None:
        report["worst"] = worst
    return report


# --- shadow scoring: candidate vs live on the captured sample ---


def _shadow_rounds_counter() -> "_metrics.Counter":
    return _metrics.get_registry().counter(
        "pio_shadow_rounds_total",
        "Shadow-scored continuous-training rounds by verdict",
        labels=("verdict",),
    )


def shadow_score(
    engine,
    storage,
    live_instance_id: str,
    candidate_instance_id: str,
    records: List[dict],
    min_jaccard: float = 0.0,
    batch: int = 64,
) -> Dict[str, Any]:
    """Score a candidate model instance against the live one on the
    captured query sample. Runs on the continuous-train loop ONLY —
    never on the serving path. Returns the per-round quality verdict
    (``comparable`` when the mean jaccard clears ``min_jaccard``,
    ``diverged`` otherwise) and records the ``pio_shadow_*`` families.
    """
    from predictionio_tpu.api.engine_server import DeployedEngine

    t0 = time.perf_counter()
    live = DeployedEngine.from_storage(
        engine, storage, engine_instance_id=live_instance_id
    )
    candidate = DeployedEngine.from_storage(
        engine, storage, engine_instance_id=candidate_instance_id
    )
    live_results = list(_serve_records(live, records, batch=batch))
    cand_results = list(_serve_records(candidate, records, batch=batch))
    n = 0
    jaccards: List[float] = []
    displacements: List[float] = []
    score_deltas: List[float] = []
    for (li, ls), (ci, cs) in zip(live_results, cand_results):
        cmp = compare_topn(li, ls, ci, cs)
        n += 1
        jaccards.append(cmp["jaccard"])
        displacements.append(cmp["rank_displacement"])
        score_deltas.append(cmp["score_delta"])
    jaccard_mean = (sum(jaccards) / n) if n else 1.0
    verdict = "comparable" if jaccard_mean >= min_jaccard else "diverged"
    report = {
        "verdict": verdict,
        "queries": n,
        "liveVersion": live_instance_id,
        "candidateVersion": candidate_instance_id,
        "jaccard_mean": jaccard_mean,
        "jaccard_min": min(jaccards) if jaccards else 1.0,
        "rank_displacement_mean": (
            (sum(displacements) / n) if n else 0.0
        ),
        "score_delta_mean": (sum(score_deltas) / n) if n else 0.0,
        "wall_s": round(time.perf_counter() - t0, 4),
    }
    reg = _metrics.get_registry()
    _shadow_rounds_counter().labels(verdict=verdict).inc()
    reg.counter(
        "pio_shadow_queries_total",
        "Captured queries scored by shadow evaluation",
    ).inc(n)
    reg.gauge(
        "pio_shadow_last_jaccard",
        "Mean jaccard of the latest shadow-scored round "
        "(candidate vs live on the captured sample)",
    ).set(jaccard_mean)
    reg.gauge(
        "pio_shadow_last_rank_displacement",
        "Mean rank displacement of the latest shadow-scored round",
    ).set(report["rank_displacement_mean"])
    reg.gauge(
        "pio_shadow_last_score_delta",
        "Mean score delta of the latest shadow-scored round",
    ).set(report["score_delta_mean"])
    return report


# THE process-global capture ring and attribution table (one per worker
# process, like the metrics/tracing/health registries; bounded by
# construction). Servers and the continuous-train loop share them.
_CAPTURE = PredictionCapture()
_ATTRIBUTION = AttributionTable()


def get_capture() -> PredictionCapture:
    return _CAPTURE


def get_attribution() -> AttributionTable:
    return _ATTRIBUTION
