"""WorkflowContext: the per-run compute/storage context.

The reference creates one SparkContext per workflow run
(core/.../workflow/WorkflowContext.scala:26-45 — app name
"PredictionIO <mode>: <batch>", env passthrough). The TPU analog carries:

- ``storage`` — the configured Storage universe (event + metadata + models)
- ``mesh``    — the `jax.sharding.Mesh` the run's kernels shard over
- ``mode`` / ``batch`` — labels for logging and instance records

The mesh is constructed lazily on first access so host-only workflows
(event import, metadata admin) never touch the accelerator.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

logger = logging.getLogger(__name__)


class WorkflowContext:
    def __init__(
        self,
        mode: str = "",
        batch: str = "",
        storage=None,
        mesh=None,
        env: Optional[Dict[str, str]] = None,
    ):
        from predictionio_tpu.utils.profiling import PhaseTimer

        self.mode = mode
        self.batch = batch
        self.env = dict(env or {})
        self._storage = storage
        self._mesh = mesh
        # per-run phase timers (SURVEY.md §5: first-class observability)
        self.timer = PhaseTimer()

    @property
    def app_name(self) -> str:
        return f"PredictionIO-TPU {self.mode}: {self.batch}"

    @property
    def storage(self):
        if self._storage is None:
            from predictionio_tpu.data.storage import get_storage

            self._storage = get_storage()
        return self._storage

    @property
    def mesh(self):
        if self._mesh is None:
            from predictionio_tpu.parallel import default_mesh
            from predictionio_tpu.utils.compilation_cache import (
                ensure_compilation_cache,
            )

            # first accelerator touch of the run: make compiled
            # executables persistent so repeat trains/evals/deploys skip
            # the multi-second XLA compile (no reference analog — the
            # JVM substrate has no compilation step)
            ensure_compilation_cache()
            self._mesh = default_mesh()
            logger.info(
                "%s: created %s", self.app_name, dict(self._mesh.shape)
            )
        return self._mesh

    def stop(self) -> None:
        """SparkContext.stop analog — nothing to tear down; the mesh is a
        device view, not a resource."""
        self._mesh = None


def workflow_context(
    mode: str = "", batch: str = "", storage=None, mesh=None, env=None
) -> WorkflowContext:
    """Factory mirroring reference WorkflowContext.apply."""
    return WorkflowContext(mode=mode, batch=batch, storage=storage, mesh=mesh, env=env)
