"""Zero-downtime model promotion: the gated retrain→swap→rollback pipeline.

PR 5 gave the continuous-train loop, PR 8 gave ``/reload`` factor
re-upload, PR 11 gave the shadow-scoring verdict — three unconnected
pieces, so a bad retrain could be swapped live and a crash mid-promotion
could strand a fleet. This module joins them into the production
promotion pipeline the reference's CreateServer hot-reload contract
implies (SURVEY.md; the ALX paper, arXiv:2112.02194, motivates swapping
*behind* the resident-factor serving tier rather than restarting it):

1. **Gate** — the round's shadow verdict (workflow/quality.shadow_score)
   is a HARD gate: ``diverged`` ⇒ refuse the swap, count
   ``pio_promotion_total{outcome="refused"}``, keep serving the live
   instance. ``PromotionConfig.require_shadow`` additionally refuses
   rounds with no shadow sample at all.
2. **Persist check** — the candidate must be a COMPLETED engine instance
   with a persisted model blob (CoreWorkflow.run_train's output); a
   crash between train and promotion surfaces here as a clean refusal,
   never a half-promoted state.
3. **Prepare / warm** — the candidate's serving state is built and
   compiled OFF the hot path (``DeployedEngine.from_storage`` →
   ``prepare_serving`` → ``ItemRetriever.warm()``); live traffic keeps
   flowing on the old instance throughout.
4. **Swap** — one atomic reference swap behind the in-flight batch
   boundary (in-process: ``QueryAPI.bind_deployed``; fleet: per-worker
   ``POST /reload`` with the TARGET engine-instance id, so an
   SO_REUSEPORT fleet converges on ONE pinned version instead of racing
   "latest").
5. **Drain** — the old ``DeployedEngine`` drains: its resident device
   factors are freed only after its last in-flight batch resolves
   (``DeployedEngine.drain``/``release``), under a bounded-drain
   watchdog heartbeat (``promotion`` in the health registry — a wedged
   drain degrades ``/readyz``). The drained previous instance is
   RETAINED in the server's small LRU of prepared serving states (the
   reference's multi-variant admin tier) so rollback is instant.
6. **Observe / rollback** — a post-swap observation window watches the
   per-version ``pio_serving_*`` / ``pio_online_attributed_total``
   families and the HTTP error counters; a regression re-swaps to the
   retained previous instance and counts
   ``pio_promotion_total{outcome="rolled_back"}``.

Every stage boundary carries a named fault-injection hook (the
``le.compact_fault`` / ``commit_fault`` idiom — see :data:`FAULT_STAGES`
and :attr:`PromotionPipeline.faults`): a crash or exception injected
between train↔persist, persist↔warm, warm↔swap, swap↔drain, or during
rollback must leave the fleet serving ONE consistent version with zero
dropped queries and no leaked device buffers — asserted by
tests/test_promotion.py and the ``promotion_under_load`` bench config.
"""

from __future__ import annotations

import dataclasses
import logging
import re
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Callable, Dict, Optional, Sequence

from predictionio_tpu.utils import health as _health
from predictionio_tpu.utils import metrics as _metrics

logger = logging.getLogger(__name__)

__all__ = [
    "FAULT_STAGES",
    "PROMOTION_OUTCOMES",
    "FleetTarget",
    "InProcessTarget",
    "PromotionConfig",
    "PromotionPipeline",
    "promotion_stats",
]

# The named fault-injection points, in pipeline order. Each names the
# boundary it sits ON: "train_persist" fires between the train that
# produced the candidate and the pipeline's persist check, and so on.
# Tests (and the promotion_under_load bench) set
# ``pipeline.faults[stage] = raiser`` and assert the fleet stays on one
# consistent version.
FAULT_STAGES = (
    "train_persist",
    "persist_warm",
    "warm_swap",
    "swap_drain",
    "rollback",
)

PROMOTION_OUTCOMES = (
    "promoted",
    "refused",
    "failed",
    "rolled_back",
    "skipped",
)

# a healthy promotion (prepare+warm of a production model) takes
# seconds-to-minutes; the watchdog deadline must exceed any healthy run.
# Tests tighten hb.deadline_s directly (utils/health.py contract).
PROMOTION_DEADLINE_S = 900.0


def _promotion_counter() -> "_metrics.Counter":
    return _metrics.get_registry().counter(
        "pio_promotion_total",
        "Model-promotion pipeline runs by outcome (refused = the shadow "
        "gate blocked the swap; rolled_back = the post-swap observation "
        "window re-swapped to the previous instance)",
        labels=("outcome",),
    )


def _stage_seconds() -> "_metrics.Histogram":
    return _metrics.get_registry().histogram(
        "pio_promotion_stage_seconds",
        "Wall clock of each promotion-pipeline stage",
        labels=("stage",),
        buckets=_metrics.LATENCY_BUCKETS_S,
    )


def _drain_seconds() -> "_metrics.Histogram":
    return _metrics.get_registry().histogram(
        "pio_promotion_drain_seconds",
        "Time for the displaced instance's last in-flight batch to "
        "resolve after a swap",
        buckets=_metrics.LATENCY_BUCKETS_S,
    )


def promotion_stats() -> Dict[str, int]:
    """Lifetime promotion outcome counts from the registry (surfaced in
    the engine server's status.json and the bench summary)."""
    c = _promotion_counter()
    return {k: int(c.labels(outcome=k).value) for k in PROMOTION_OUTCOMES}


@dataclasses.dataclass
class PromotionConfig:
    """Promotion/rollback policy knobs (docs/OBSERVABILITY.md documents
    the full contract)."""

    # bounded drain of the displaced instance: past this the pipeline
    # stops waiting (the instance is released at LRU eviction instead —
    # buffers are freed by refcount, never under an in-flight batch)
    drain_timeout_s: float = 30.0
    # post-swap observation window; 0 disables observation + rollback
    observe_s: float = 5.0
    observe_poll_s: float = 0.25
    # rollback when window-5xx / max(window candidate requests, 1)
    # exceeds this (and at least one error happened)
    max_error_rate: float = 0.05
    # rollback when the candidate's attributed hit rate over the window
    # falls below min_hit_rate_ratio x the previous version's lifetime
    # hit rate — judged only once BOTH sides have >= min_attributed
    # attributed (converted+miss) events
    min_hit_rate_ratio: float = 0.5
    min_attributed: int = 20
    # refuse rounds that produced no shadow sample at all (default:
    # promote — the first rounds of a fresh deploy have no capture yet)
    require_shadow: bool = False
    # telemetry collector base URL (pio collector): when set, the
    # observation window reads the FLEET-wide federated /metrics from
    # the collector — 5xx and per-version request/attribution counters
    # summed across every worker AND the event server — instead of the
    # one process the target can see. Size observe_s to at least two
    # collector poll intervals so the window spans a fresh scrape; an
    # unreachable collector falls back to the target's own sample (and
    # logs), never fails the promotion.
    collector_url: Optional[str] = None
    collector_timeout_s: float = 5.0


# --- observation: the per-version serving/quality/error sample ---

_LABEL_RE_CACHE: Dict[str, "re.Pattern"] = {}


def _label_value(sample_key: str, label: str) -> Optional[str]:
    pat = _LABEL_RE_CACHE.get(label)
    if pat is None:
        pat = re.compile(rf'{label}="([^"]*)"')
        _LABEL_RE_CACHE[label] = pat
    m = pat.search(sample_key)
    return m.group(1) if m else None


def _empty_sample() -> Dict[str, Any]:
    return {"errors_5xx": 0.0, "requests": {}, "attributed": {}}


def _fold_sample(
    out: Dict[str, Any], family: str, labels: Dict[str, Optional[str]],
    value: float,
) -> None:
    """Fold one counter sample into the observation dict — shared by the
    in-process registry walk and the fleet /metrics scrape."""
    if family == "pio_http_errors_total":
        status = labels.get("status") or ""
        server = labels.get("server") or ""
        if status.startswith("5") and "Engine" in server:
            out["errors_5xx"] += value
    elif family == "pio_serving_requests_total":
        v = labels.get("version") or "unknown"
        out["requests"][v] = out["requests"].get(v, 0.0) + value
    elif family == "pio_online_attributed_total":
        key = (labels.get("version") or "unknown", labels.get("outcome") or "")
        out["attributed"][key] = out["attributed"].get(key, 0.0) + value


def _registry_observation() -> Dict[str, Any]:
    """The observation sample read straight from THIS process's
    registry (the in-process target's serving metrics live here)."""
    out = _empty_sample()
    for fam in _metrics.get_registry().families():
        if fam.name not in (
            "pio_http_errors_total",
            "pio_serving_requests_total",
            "pio_online_attributed_total",
        ):
            continue
        for values, child in fam.children():
            labels = dict(zip(fam.label_names, values))
            _fold_sample(out, fam.name, labels, child.value)
    return out


def _scraped_observation(samples: Dict[str, float]) -> Dict[str, Any]:
    """The same sample folded from a parsed /metrics exposition."""
    out = _empty_sample()
    for key, value in samples.items():
        family = key.split("{", 1)[0]
        if family not in (
            "pio_http_errors_total",
            "pio_serving_requests_total",
            "pio_online_attributed_total",
        ):
            continue
        labels = {
            name: _label_value(key, name)
            for name in ("server", "route", "status", "version", "outcome")
        }
        _fold_sample(out, family, labels, value)
    return out


def _sample_delta(after: Dict[str, Any], before: Dict[str, Any]) -> Dict[str, Any]:
    out = _empty_sample()
    out["errors_5xx"] = max(0.0, after["errors_5xx"] - before["errors_5xx"])
    for v, n in after["requests"].items():
        d = n - before["requests"].get(v, 0.0)
        if d:
            out["requests"][v] = d
    for k, n in after["attributed"].items():
        d = n - before["attributed"].get(k, 0.0)
        if d:
            out["attributed"][k] = d
    return out


def _collector_observation(url: str, timeout_s: float) -> Dict[str, Any]:
    """The observation sample folded from a telemetry collector's
    FEDERATED ``/metrics`` (utils/telemetry.py): counters there are
    already summed across every fleet target, so the standard scrape
    fold sees the whole fleet's error/request/attribution deltas in one
    read — the cross-process view the per-process targets structurally
    cannot provide."""
    from predictionio_tpu.utils.metrics import parse_exposition

    with urllib.request.urlopen(
        url.rstrip("/") + "/metrics", timeout=timeout_s
    ) as resp:
        return _scraped_observation(
            parse_exposition(resp.read().decode("utf-8"))
        )


def _hit_rate(attributed: Dict, version: str) -> Optional[float]:
    converted = attributed.get((version, "converted"), 0.0)
    missed = attributed.get((version, "miss"), 0.0)
    denom = converted + missed
    return (converted / denom) if denom else None


def _attributed_count(attributed: Dict, version: str) -> float:
    return attributed.get((version, "converted"), 0.0) + attributed.get(
        (version, "miss"), 0.0
    )


# --- targets: where the swap actually lands ---


class InProcessTarget:
    """Promotion target for an in-process :class:`EngineServer` (the
    single-box / bench / test shape): swap = ``bind_deployed`` behind
    the in-flight batch boundary; the displaced snapshot goes into the
    server's retained-LRU (released at eviction); rollback pops the
    retained previous state back — no recompile, no store read."""

    def __init__(self, server):
        self.server = server

    def current_version(self) -> str:
        from predictionio_tpu.api.engine_server import _version_of

        return _version_of(self.server.api.deployed)

    def prepare(self, engine_instance_id: str):
        """Build + warm the candidate's serving state off the hot path
        (the server keeps serving the live instance meanwhile)."""
        from predictionio_tpu.api.engine_server import DeployedEngine

        return DeployedEngine.from_storage(
            self.server.engine,
            self.server.storage,
            engine_instance_id=engine_instance_id,
            ctx=self.server._serving_ctx,
        )

    def swap(self, prepared):
        """Atomic reference swap; returns the displaced DeployedEngine
        (now retained in the server's LRU for rollback)."""
        return self.server.swap_deployed(prepared)

    def drain(self, displaced, timeout_s: float, hb) -> bool:
        """Bounded wait for the displaced instance's last in-flight
        batch; beats the watchdog only on PROGRESS, so a truly wedged
        drain degrades /readyz once the deadline passes."""
        return displaced.drain(timeout_s, on_progress=hb.beat)

    def rollback(self, displaced, previous_version: str) -> None:
        self.server.reload(engine_instance_id=previous_version)

    def discard(self, prepared) -> None:
        """Release a prepared-but-never-swapped candidate (a fault
        between warm and swap must not leak its device buffers; nothing
        can be in flight on a never-bound snapshot)."""
        prepared.release(timeout_s=1.0)

    def observe_sample(self) -> Dict[str, Any]:
        return _registry_observation()


class FleetTarget:
    """Promotion target for a deployed serving fleet, driven over HTTP.

    ``urls`` are the fleet's base URLs. With per-worker ports, each URL
    is one worker; with an SO_REUSEPORT fleet sharing one port, the
    kernel routes every request to an arbitrary worker — so the
    converge loop below keeps (a) re-POSTing ``/reload`` with the
    TARGET engine-instance id (idempotent: a worker already on the
    target answers without re-deploying) and (b) polling
    ``/status.json`` until ``confirms`` consecutive sweeps all report
    the target version. Pinning the id is what makes this safe: no
    worker can ever land on a *different* version than the one this
    pipeline chose, however requests are balanced."""

    def __init__(
        self,
        urls: Sequence[str],
        workers_per_url: int = 1,
        timeout_s: float = 10.0,
        converge_timeout_s: float = 60.0,
        confirms: Optional[int] = None,
    ):
        if not urls:
            raise ValueError("FleetTarget needs at least one URL")
        self.urls = [u.rstrip("/") for u in urls]
        self.workers_per_url = max(1, int(workers_per_url))
        self.timeout_s = float(timeout_s)
        self.converge_timeout_s = float(converge_timeout_s)
        # enough consecutive all-match sweeps that every worker behind a
        # shared port has (probabilistically) answered at least once
        self.confirms = (
            int(confirms)
            if confirms is not None
            else max(3, 2 * self.workers_per_url)
        )

    # -- http plumbing --

    def _status_version(self, url: str) -> str:
        with urllib.request.urlopen(
            f"{url}/status.json", timeout=self.timeout_s
        ) as resp:
            import json

            return str(json.load(resp).get("modelVersion") or "unknown")

    def _post_reload(self, url: str, version: str) -> None:
        req = urllib.request.Request(
            f"{url}/reload?"
            + urllib.parse.urlencode({"engineInstanceId": version}),
            data=b"",
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                return
        except urllib.error.HTTPError as e:
            # a 500 names the cause (store down, missing instance) — the
            # worker kept its old snapshot; surface it instead of
            # spinning the converge loop against a doomed reload
            detail = ""
            try:
                detail = e.read().decode("utf-8", "replace")[:300]
            except Exception:
                logger.debug("reload error body unreadable", exc_info=True)
            raise RuntimeError(
                f"worker {url} refused reload to {version}: {detail}"
            ) from e

    def _converge(self, version: str, timeout_s: Optional[float] = None) -> None:
        deadline = time.monotonic() + (
            self.converge_timeout_s if timeout_s is None else timeout_s
        )
        streak = 0
        last_err: Optional[str] = None
        while time.monotonic() < deadline:
            all_match = True
            for url in self.urls:
                # a TRANSIENT member failure (the supervisor restarting a
                # crashed worker, a connection blip) is "not converged
                # yet", not a doomed swap — keep sweeping until the
                # deadline. Only a worker actively REFUSING the reload
                # (the _post_reload 500 → RuntimeError) aborts fast.
                try:
                    v = self._status_version(url)
                    if v != version:
                        all_match = False
                        self._post_reload(url, version)
                except RuntimeError:
                    raise
                except Exception as e:
                    all_match = False
                    last_err = f"{url}: {type(e).__name__}: {e}"
                    logger.warning(
                        "converge sweep: %s unreachable (%s); retrying "
                        "until the deadline",
                        url, e,
                    )
            if all_match:
                streak += 1
                if streak >= self.confirms:
                    return
            else:
                streak = 0
            time.sleep(0.1)
        raise RuntimeError(
            f"fleet did not converge on engine instance {version} within "
            f"{self.converge_timeout_s}s"
            + (f" (last member error: {last_err})" if last_err else "")
        )

    # -- the target protocol --

    def current_version(self) -> str:
        versions = {self._status_version(u) for u in self.urls}
        if len(versions) == 1:
            return versions.pop()
        # a split fleet (a crashed mid-promotion predecessor): report
        # one member deterministically; the next swap's pinned converge
        # heals the split
        logger.warning("fleet reports mixed versions %s", sorted(versions))
        return sorted(versions)[0]

    def prepare(self, engine_instance_id: str):
        # workers build + warm their own serving state inside /reload
        # (off their hot paths); the handle is just the pinned id
        return engine_instance_id

    def swap(self, prepared):
        previous = self.current_version()
        try:
            self._converge(prepared)
        except Exception:
            # a half-converged fleet is the one inconsistent state this
            # pipeline must never leave behind: best-effort revert every
            # worker to the previous pinned version before re-raising
            logger.exception(
                "fleet swap to %s failed; reverting to %s", prepared, previous
            )
            for url in self.urls:
                try:
                    self._post_reload(url, previous)
                except Exception:
                    logger.exception("revert nudge to %s failed", url)
            raise
        return previous

    def drain(self, displaced, timeout_s: float, hb) -> bool:
        # each worker drains + releases its displaced snapshot behind
        # its own /reload (EngineServer._retire); nothing to wait on
        # from here
        return True

    def rollback(self, displaced, previous_version: str) -> None:
        self._converge(previous_version)

    def discard(self, prepared) -> None:
        return None

    def observe_sample(self) -> Dict[str, Any]:
        from predictionio_tpu.utils.metrics import parse_exposition

        out = _empty_sample()
        for url in self.urls:
            try:
                with urllib.request.urlopen(
                    f"{url}/metrics", timeout=self.timeout_s
                ) as resp:
                    samples = parse_exposition(
                        resp.read().decode("utf-8")
                    )
            except Exception:
                logger.warning(
                    "observation scrape of %s failed", url, exc_info=True
                )
                continue
            member = _scraped_observation(samples)
            out["errors_5xx"] += member["errors_5xx"]
            for v, n in member["requests"].items():
                out["requests"][v] = out["requests"].get(v, 0.0) + n
            for k, n in member["attributed"].items():
                out["attributed"][k] = out["attributed"].get(k, 0.0) + n
        return out


# --- the pipeline ---


class PromotionPipeline:
    """Drives one candidate instance through
    gate→persist→prepare→swap→drain→observe(→rollback).

    ``promote`` NEVER raises on an ordinary failure — any stage
    exception is caught, counted as ``outcome="failed"`` with the stage
    named, and the target is left serving ONE consistent version (the
    old one for pre-swap failures, the candidate for post-swap ones; a
    prepared-but-unswapped candidate is released). Only
    ``BaseException`` (process death, the crash-consistency tests' kill
    signal) propagates — and because the swap itself is a single atomic
    reference flip (or a pinned-id converge), a kill at ANY fault point
    still leaves no half-promoted state for the next round to trip on.
    """

    def __init__(
        self,
        target,
        config: Optional[PromotionConfig] = None,
        storage=None,
    ):
        self.target = target
        self.config = config or PromotionConfig()
        self.storage = storage
        # the named fault-injection hooks (le.compact_fault idiom):
        # tests assign callables that raise; production leaves them None
        self.faults: Dict[str, Optional[Callable[[], None]]] = {
            stage: None for stage in FAULT_STAGES
        }

    def _fault(self, stage: str) -> None:
        fn = self.faults.get(stage)
        if fn is not None:
            fn()

    def _verify_persisted(self, instance_id: str) -> None:
        """The persist gate: a candidate is promotable only as a
        COMPLETED instance with a persisted model blob. (run_train wrote
        both; a crash between train and promotion — the train_persist
        fault point — surfaces here as a clean failure.)"""
        if self.storage is None:
            return
        instance = self.storage.get_meta_data_engine_instances().get(
            instance_id
        )
        if instance is None or instance.status != "COMPLETED":
            raise RuntimeError(
                f"candidate {instance_id!r} is not a COMPLETED engine "
                f"instance (status {getattr(instance, 'status', None)!r})"
            )
        if self.storage.get_model_data_models().get(instance_id) is None:
            raise RuntimeError(
                f"candidate {instance_id!r} has no persisted model blob"
            )

    def _observe(
        self, candidate: str, previous: str, hb
    ) -> Optional[str]:
        """The post-swap observation window. Returns a rollback reason,
        or None when the candidate held up.

        The sample SOURCE is pinned for the whole window: when a
        collector is configured and its first (``before``) fetch
        succeeds, the ``after`` sample MUST come from the collector
        too — mixing a fleet-wide ``before`` with a single-process
        ``after`` (or vice versa) produces garbage deltas that can
        promote a bad candidate or roll back a healthy one. A collector
        that dies mid-window makes the window INCONCLUSIVE (no
        rollback, logged) rather than silently judged against the
        wrong denominator; a collector that is already unreachable at
        window start degrades to the target's own sample for BOTH
        sides."""
        cfg = self.config
        if cfg.observe_s <= 0:
            return None
        use_collector = bool(cfg.collector_url)
        if use_collector:
            try:
                before = _collector_observation(
                    cfg.collector_url, cfg.collector_timeout_s
                )
            except Exception:
                logger.warning(
                    "collector %s unreachable at observation start; the "
                    "window falls back to the target's own sample",
                    cfg.collector_url, exc_info=True,
                )
                use_collector = False
        if not use_collector:
            before = self.target.observe_sample()
        end = time.monotonic() + cfg.observe_s
        while time.monotonic() < end:
            hb.beat()
            time.sleep(min(cfg.observe_poll_s, max(0.0, end - time.monotonic())))
        if use_collector:
            try:
                after = _collector_observation(
                    cfg.collector_url, cfg.collector_timeout_s
                )
            except Exception:
                logger.warning(
                    "collector %s died mid-observation; the window is "
                    "inconclusive (no rollback) — a target-sample "
                    "'after' would be judged against a fleet-wide "
                    "'before'",
                    cfg.collector_url, exc_info=True,
                )
                return None
        else:
            after = self.target.observe_sample()
        window = _sample_delta(after, before)
        cand_requests = window["requests"].get(candidate, 0.0)
        errors = window["errors_5xx"]
        error_rate = errors / max(cand_requests, 1.0)
        if errors > 0 and error_rate > cfg.max_error_rate:
            return (
                f"error rate {error_rate:.4f} over the observation window "
                f"({int(errors)} 5xx / {int(cand_requests)} candidate "
                f"requests) exceeds {cfg.max_error_rate:.4f}"
            )
        # quality: candidate's window hit rate vs the previous version's
        # lifetime hit rate (post-swap conversions still attribute to
        # the previous version's pre-swap serves — its lifetime rate is
        # the natural baseline)
        cand_rate = _hit_rate(window["attributed"], candidate)
        prev_rate = _hit_rate(after["attributed"], previous)
        if (
            cand_rate is not None
            and prev_rate is not None
            and prev_rate > 0
            and _attributed_count(window["attributed"], candidate)
            >= cfg.min_attributed
            and _attributed_count(after["attributed"], previous)
            >= cfg.min_attributed
            and cand_rate < prev_rate * cfg.min_hit_rate_ratio
        ):
            return (
                f"attributed hit rate {cand_rate:.4f} fell below "
                f"{cfg.min_hit_rate_ratio:.2f}x the previous version's "
                f"{prev_rate:.4f}"
            )
        return None

    def promote(
        self,
        candidate_instance_id: str,
        shadow: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Run the full pipeline for one trained candidate. Returns the
        promotion report (outcome, stage timings, the version the
        target is serving afterwards)."""
        cfg = self.config
        t_start = time.perf_counter()
        report: Dict[str, Any] = {
            "candidate": candidate_instance_id,
            "outcome": "failed",
            "stage": None,
            "stages": {},
        }
        hb = _health.heartbeat("promotion", deadline_s=PROMOTION_DEADLINE_S)
        stage = "gate"
        prepared = None
        swapped = False

        def _end_stage(name: str, t0: float) -> None:
            dt = time.perf_counter() - t0
            report["stages"][name] = round(dt, 4)
            _stage_seconds().labels(stage=name).observe(dt)

        try:
            with hb.busy():
                self._fault("train_persist")
                previous_version = self.target.current_version()
                report["previous"] = previous_version
                if candidate_instance_id == previous_version:
                    report["outcome"] = "skipped"
                    report["reason"] = "candidate already serving"
                    return report
                # 1. the shadow gate
                t0 = time.perf_counter()
                verdict = (shadow or {}).get("verdict")
                report["verdict"] = verdict
                if verdict == "diverged":
                    report["outcome"] = "refused"
                    report["reason"] = (
                        "shadow verdict diverged (jaccard "
                        f"{(shadow or {}).get('jaccard_mean')})"
                    )
                    return report
                if shadow is None and cfg.require_shadow:
                    report["outcome"] = "refused"
                    report["reason"] = (
                        "no shadow sample and require_shadow is set"
                    )
                    return report
                _end_stage("gate", t0)
                # 2. the persist gate
                stage = "persist"
                t0 = time.perf_counter()
                self._verify_persisted(candidate_instance_id)
                _end_stage("persist", t0)
                self._fault("persist_warm")
                # 3. prepare + warm off the hot path
                stage = "prepare"
                t0 = time.perf_counter()
                prepared = self.target.prepare(candidate_instance_id)
                _end_stage("prepare", t0)
                hb.beat()
                self._fault("warm_swap")
                # 4. the atomic swap
                stage = "swap"
                t0 = time.perf_counter()
                displaced = self.target.swap(prepared)
                swapped = True
                _end_stage("swap", t0)
                self._fault("swap_drain")
                # 5. bounded drain of the displaced instance
                stage = "drain"
                t0 = time.perf_counter()
                drained = self.target.drain(
                    displaced, cfg.drain_timeout_s, hb
                )
                _end_stage("drain", t0)
                _drain_seconds().observe(time.perf_counter() - t0)
                report["drained"] = bool(drained)
                # HBM residency of the displaced instance after drain
                # (utils/device_ledger.py): it stays RETAINED (warm,
                # factors resident) for instant rollback, so nonzero is
                # the healthy state here — release at LRU eviction (or
                # server shutdown) drives it to zero, and
                # DeployedEngine.release() asserts exactly that,
                # counting violations in pio_device_ledger_leaks_total.
                ledger_bytes = getattr(displaced, "ledger_bytes", None)
                if callable(ledger_bytes):
                    try:
                        report["displaced_ledger_bytes"] = int(
                            ledger_bytes()
                        )
                    except Exception:
                        logger.debug(
                            "displaced ledger read failed", exc_info=True
                        )
                # HBM the continuous trainer keeps resident between
                # rounds (ops/streaming.ResidentPack, train-pack
                # component): nonzero under a live continuous loop,
                # zero otherwise — reported beside the displaced
                # instance so an operator sees the full post-swap HBM
                # retention picture in one place.
                try:
                    from predictionio_tpu.utils.device_ledger import (
                        get_ledger,
                    )

                    report["resident_pack_bytes"] = int(
                        get_ledger().total_bytes(component="train-pack")
                    )
                except Exception:
                    logger.debug(
                        "resident-pack ledger read failed", exc_info=True
                    )
                if not drained:
                    logger.warning(
                        "displaced instance %s did not drain within %.1fs; "
                        "its buffers are freed at LRU eviction once the "
                        "straggler batch resolves",
                        previous_version, cfg.drain_timeout_s,
                    )
                # 6. observation window → rollback
                stage = "observe"
                t0 = time.perf_counter()
                regression = self._observe(
                    candidate_instance_id, previous_version, hb
                )
                _end_stage("observe", t0)
                if regression is not None:
                    stage = "rollback"
                    report["reason"] = regression
                    self._fault("rollback")
                    t0 = time.perf_counter()
                    self.target.rollback(displaced, previous_version)
                    _end_stage("rollback", t0)
                    report["outcome"] = "rolled_back"
                    logger.warning(
                        "promotion of %s ROLLED BACK to %s: %s",
                        candidate_instance_id, previous_version, regression,
                    )
                    return report
                report["outcome"] = "promoted"
                logger.info(
                    "promoted engine instance %s (previous %s retained)",
                    candidate_instance_id, previous_version,
                )
                return report
        except Exception as e:
            # an ordinary failure never escapes: serving stays on ONE
            # consistent version (old pre-swap, candidate post-swap)
            report["outcome"] = "failed"
            report["stage"] = stage
            report["error"] = f"{type(e).__name__}: {e}"
            logger.exception(
                "promotion of %s failed at stage %r; fleet keeps serving "
                "a consistent version",
                candidate_instance_id, stage,
            )
            if prepared is not None and not swapped:
                try:
                    self.target.discard(prepared)
                except Exception:
                    logger.exception("discarding prepared candidate failed")
            return report
        finally:
            _promotion_counter().labels(outcome=report["outcome"]).inc()
            report["wall_s"] = round(time.perf_counter() - t_start, 4)
            try:
                report["serving"] = self.target.current_version()
            except Exception:
                # a dead fleet member must not mask the outcome already
                # recorded above
                report["serving"] = None
                logger.exception("could not read post-promotion version")
