"""WorkflowParams (reference core/.../workflow/WorkflowParams.scala:27-42)."""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class WorkflowParams:
    batch: str = ""
    verbose: int = 2
    save_model: bool = True
    skip_sanity_check: bool = False
    stop_after_read: bool = False
    stop_after_prepare: bool = False
