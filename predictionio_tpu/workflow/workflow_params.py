"""WorkflowParams (reference core/.../workflow/WorkflowParams.scala:27-42)."""

from __future__ import annotations

import dataclasses


from typing import Optional


@dataclasses.dataclass
class WorkflowParams:
    batch: str = ""
    verbose: int = 2
    save_model: bool = True
    skip_sanity_check: bool = False
    stop_after_read: bool = False
    stop_after_prepare: bool = False
    # TPU additions: jax.profiler trace output dir (None disables).
    # Rides utils/profiling's shared capture machinery — the same
    # session path the servers' POST /debug/profile endpoint uses, so a
    # CLI-launched capture (`pio train --profile-dir`) and an
    # HTTP-triggered one produce identical trace layouts, and the two
    # serialize on one process-wide profiler session lock.
    profile_dir: Optional[str] = None
    # Concurrent workers for the per-EngineParams evaluation grid — the
    # reference's `.par` over param sets (MetricEvaluator.scala:221-230).
    # Host stages (reads, bucketization, python glue) overlap while device
    # programs queue; <=1 runs the grid serially.
    eval_parallelism: int = 4
    # Device-side grid training (BaseAlgorithm.train_grid): variants
    # differing only in an algorithm's GRID_AXES train in one vmapped
    # program. "auto" enables it except on the CPU backend, where
    # dispatch is cheap and the batched program compiles/executes slower
    # than per-variant trains; "always"/"never" force it either way.
    grid_train: str = "auto"
    # Multi-variant evaluation upgrades a plain Engine to FastEvalEngine
    # (stage memoization + the grid_train path). The caches retain every
    # variant's models and served results for the sweep's duration —
    # set False on very large grids where that working set won't fit.
    fast_eval: bool = True

    def __post_init__(self):
        if self.grid_train not in ("auto", "always", "never"):
            raise ValueError(
                f"grid_train must be auto/always/never, got {self.grid_train!r}"
            )
