"""WorkflowParams (reference core/.../workflow/WorkflowParams.scala:27-42)."""

from __future__ import annotations

import dataclasses


from typing import Optional


@dataclasses.dataclass
class WorkflowParams:
    batch: str = ""
    verbose: int = 2
    save_model: bool = True
    skip_sanity_check: bool = False
    stop_after_read: bool = False
    stop_after_prepare: bool = False
    # TPU additions: jax.profiler trace output dir (None disables)
    profile_dir: Optional[str] = None
    # Concurrent workers for the per-EngineParams evaluation grid — the
    # reference's `.par` over param sets (MetricEvaluator.scala:221-230).
    # Host stages (reads, bucketization, python glue) overlap while device
    # programs queue; <=1 runs the grid serially.
    eval_parallelism: int = 4
