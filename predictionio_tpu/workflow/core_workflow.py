"""CoreWorkflow: train/eval lifecycle with instance records + persistence.

Capability parity with reference core/.../workflow/CoreWorkflow.scala:
``run_train`` (:42-93 — context creation, engine.train, model serialization
into MODELDATA, EngineInstance INIT->COMPLETED, stop-after interruption
handling) and ``run_evaluation`` (:96-152 — EvaluationInstance record,
EvaluationWorkflow, result storage in one-liner/HTML/JSON forms). The thin
typed wrappers in reference Workflow.scala:82-135 collapse into these
functions; EvaluationWorkflow.scala:31-42 is ``run_evaluation``'s middle
two lines.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import json
import logging
import traceback
from typing import List, Optional, Sequence

from predictionio_tpu.controller.engine import (
    BaseEngine,
    Engine,
    EngineParams,
    StopAfterPrepareInterruption,
    StopAfterReadInterruption,
)
from predictionio_tpu.controller.evaluation import Evaluation
from predictionio_tpu.data.storage.base import (
    STATUS_COMPLETED,
    STATUS_EVALUATING,
    STATUS_FAILED,
    STATUS_INIT,
    STATUS_TRAINING,
    EngineInstance,
    EvaluationInstance,
    Model,
)
from predictionio_tpu.utils import profiling
from predictionio_tpu.utils.serialize import dumps_model
from predictionio_tpu.workflow.context import WorkflowContext, workflow_context
from predictionio_tpu.workflow.workflow_params import WorkflowParams

logger = logging.getLogger(__name__)


def _is_rank_zero() -> bool:
    """True unless this process is a non-zero rank of a multi-host
    runtime. Storage writes (instance records, model blobs, evaluation
    results) happen on rank 0 only — the reference's driver-writes,
    executors-compute split."""
    try:
        import jax

        return jax.process_index() == 0
    except Exception:  # backend not initializable — single host
        return True


def _eval_engine(evaluation, engine_params_list, workflow_params):
    """The engine a grid evaluation runs through. Multi-variant grids
    upgrade a plain Engine to FastEvalEngine: stage results memoize
    across shared params-prefixes and reg-axis variants train in one
    vmapped device program (BaseAlgorithm.train_grid). Results are
    identical to the plain engine — FastEval is the reference's own
    eval-only engine (FastEvalEngine.scala:42-48); it leaves it opt-in
    only because its caches cost memory (WorkflowParams.fast_eval=False
    restores that). Every host of a multi-host run resolves the SAME
    engine here so their collective sequences agree."""
    engine = evaluation.engine
    if (
        workflow_params.fast_eval
        and type(engine) is Engine
        and len(engine_params_list) > 1
    ):
        from predictionio_tpu.controller.fast_eval import FastEvalEngine

        engine = FastEvalEngine(
            engine.data_source_class_map,
            engine.preparator_class_map,
            engine.algorithm_class_map,
            engine.serving_class_map,
        )
    return engine


def _utcnow() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


class CoreWorkflow:
    @staticmethod
    def run_train(
        engine: BaseEngine,
        engine_params: EngineParams,
        engine_instance: EngineInstance,
        ctx: Optional[WorkflowContext] = None,
        workflow_params: Optional[WorkflowParams] = None,
    ) -> Optional[str]:
        """Train and persist. Returns the engine-instance id on success;
        None when interrupted by a stop-after debug flag, or on the
        worker (non-zero) ranks of a multi-host run, which compute but
        leave all storage writes to rank 0."""
        workflow_params = workflow_params or WorkflowParams()
        ctx = ctx or workflow_context(
            mode="training", batch=workflow_params.batch or engine_instance.batch
        )
        if not _is_rank_zero():
            # Worker hosts of a multi-host run participate in rank 0's
            # collectives by executing the same training program, but
            # leave every storage write to rank 0 (reference: only the
            # Spark driver writes; executors compute) — a shared store
            # would otherwise record one duplicate instance+model blob
            # per host.
            try:
                with profiling.trace(workflow_params.profile_dir):
                    engine.train(ctx, engine_params, workflow_params)
            except (
                StopAfterReadInterruption,
                StopAfterPrepareInterruption,
            ) as e:
                logger.info("training interrupted by %s", type(e).__name__)
            return None
        storage = ctx.storage
        instances = storage.get_meta_data_engine_instances()
        # record the resolved params on the instance so deploy can
        # reconstruct EngineParams (reference CreateWorkflow.scala:213-242)
        params_json = engine_params.to_json()
        instance_id = instances.insert(
            dataclasses.replace(
                engine_instance,
                status=STATUS_INIT,
                data_source_params=json.dumps(params_json["datasource"]),
                preparator_params=json.dumps(params_json["preparator"]),
                algorithms_params=json.dumps(params_json["algorithms"]),
                serving_params=json.dumps(params_json["serving"]),
            )
        )
        logger.info("run_train: engine instance %s created", instance_id)
        try:
            instances.update(
                dataclasses.replace(
                    instances.get(instance_id), status=STATUS_TRAINING
                )
            )
            with profiling.trace(workflow_params.profile_dir):
                models = engine.train(ctx, engine_params, workflow_params)
            # resource telemetry for the round: device memory_stats()
            # where the backend provides it, host RSS fallback — gauges
            # the continuous loop / hot-swap operator watches between
            # rounds (a leaking round shows here before it OOMs)
            from predictionio_tpu.utils import health as _health

            _health.record_memory_gauges()
            if ctx.timer.records:
                logger.info("training phases:\n%s", ctx.timer.summary())
                hidden = ctx.timer.overlapped_total()
                if hidden:
                    # overlapped records are pipeline busy time hidden
                    # UNDER the read/train walls above (streaming
                    # store→device path) — report what pipelining saved
                    # rather than double-counting it into the total
                    logger.info(
                        "streaming pipeline hid %.3fs of scan/pack/"
                        "compile work under the train wall clock",
                        hidden,
                    )
            if workflow_params.save_model:
                serializable = (
                    engine.make_serializable_models(
                        ctx, instance_id, engine_params, models
                    )
                    if hasattr(engine, "make_serializable_models")
                    else models
                )
                storage.get_model_data_models().insert(
                    Model(id=instance_id, models=dumps_model(serializable))
                )
            instances.update(
                dataclasses.replace(
                    instances.get(instance_id),
                    status=STATUS_COMPLETED,
                    end_time=_utcnow(),
                )
            )
            logger.info("run_train: engine instance %s completed", instance_id)
            return instance_id
        except (StopAfterReadInterruption, StopAfterPrepareInterruption) as e:
            logger.info("training interrupted by %s", type(e).__name__)
            instances.delete(instance_id)
            return None
        except Exception:
            logger.error("training failed:\n%s", traceback.format_exc())
            instances.update(
                dataclasses.replace(
                    instances.get(instance_id),
                    status=STATUS_FAILED,
                    end_time=_utcnow(),
                )
            )
            raise

    @staticmethod
    def run_evaluation(
        evaluation: Evaluation,
        engine_params_list: Sequence[EngineParams],
        evaluation_instance: Optional[EvaluationInstance] = None,
        ctx: Optional[WorkflowContext] = None,
        workflow_params: Optional[WorkflowParams] = None,
    ):
        """Evaluate a params grid; store + return the evaluator result."""
        workflow_params = workflow_params or WorkflowParams()
        engine_params_list = list(engine_params_list)  # may be a generator
        ctx = ctx or workflow_context(mode="evaluation", batch=workflow_params.batch)
        if not _is_rank_zero():
            # Worker hosts compute (joining rank 0's collectives) but
            # leave the instance record + result writes to rank 0. The
            # engine selection MUST mirror rank 0's (shared helper): a
            # FastEval rank 0 training each distinct variant once
            # alongside a plain-engine worker training per variant would
            # issue different collective sequences and deadlock the pod.
            # batch_eval holds ALL the device work; the evaluator stage
            # is host math with side effects (best.json, instance rows)
            # that must happen once — workers skip it and return None.
            engine = _eval_engine(
                evaluation, engine_params_list, workflow_params
            )
            engine.batch_eval(ctx, engine_params_list, workflow_params)
            return None
        storage = ctx.storage
        instances = storage.get_meta_data_evaluation_instances()
        if evaluation_instance is None:
            evaluation_instance = EvaluationInstance(
                id="",
                status="",
                start_time=_utcnow(),
                end_time=_utcnow(),
                evaluation_class=type(evaluation).__name__,
                batch=workflow_params.batch,
            )
        instance_id = instances.insert(
            dataclasses.replace(evaluation_instance, status=STATUS_EVALUATING)
        )
        try:
            engine = _eval_engine(
                evaluation, engine_params_list, workflow_params
            )
            # EvaluationWorkflow.runEvaluation (reference :31-42)
            engine_eval_data_set = engine.batch_eval(
                ctx, engine_params_list, workflow_params
            )
            result = evaluation.evaluator.evaluate_base(
                ctx, evaluation, engine_eval_data_set, workflow_params
            )
        except Exception:
            logger.error("evaluation failed:\n%s", traceback.format_exc())
            instances.update(
                dataclasses.replace(
                    instances.get(instance_id),
                    status=STATUS_FAILED,
                    end_time=_utcnow(),
                )
            )
            raise
        if result.no_save:
            # reference CoreWorkflow.scala:127-129 — result not inserted
            logger.info("evaluation result not inserted into database (no_save)")
            instances.delete(instance_id)
        else:
            instances.update(
                dataclasses.replace(
                    instances.get(instance_id),
                    status=STATUS_COMPLETED,
                    end_time=_utcnow(),
                    evaluator_results=result.to_one_liner(),
                    evaluator_results_html=result.to_html(),
                    evaluator_results_json=result.to_json(),
                )
            )
        logger.info(
            "run_evaluation: instance %s completed: %s",
            instance_id,
            result.to_one_liner(),
        )
        return result
