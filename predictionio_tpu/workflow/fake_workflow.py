"""FakeWorkflow: run an arbitrary function under the full workflow env.

Capability parity with the reference FakeWorkflow/FakeRun
(core/src/main/scala/io/prediction/workflow/FakeWorkflow.scala:31-106):
wrap a ``WorkflowContext -> None`` function as an Evaluation so it runs
through the normal evaluation lifecycle (``pio run`` /
``CoreWorkflow.run_evaluation``) with storage and the device mesh
configured — the dev harness for ad-hoc scripts.
"""

from __future__ import annotations

from typing import Callable

from predictionio_tpu.controller.engine import BaseEngine, EngineParams
from predictionio_tpu.controller.evaluation import (
    BaseEvaluator,
    BaseEvaluatorResult,
    Evaluation,
)


class FakeEvalResult(BaseEvaluatorResult):
    """Reference FakeEvalResult (FakeWorkflow.scala:41-48): never saved."""

    no_save = True

    def to_one_liner(self) -> str:
        return "Done running FakeWorkflow"


class _FakeEngine(BaseEngine):
    def train(self, ctx, engine_params, workflow_params):
        return []

    def batch_eval(self, ctx, engine_params_list, workflow_params):
        # one empty eval set per params so the evaluator runs once
        return [(p, []) for p in engine_params_list]

    def jvalue_to_engine_params(self, json_obj):
        return EngineParams()


class _FakeEvaluator(BaseEvaluator):
    def __init__(self, func: Callable):
        self.func = func

    def evaluate_base(self, ctx, evaluation, engine_eval_data_set, workflow_params):
        self.func(ctx)
        return FakeEvalResult()


class FakeEvaluation(Evaluation):
    """Reference FakeRun (FakeWorkflow.scala:96-106)."""

    def __init__(self, func: Callable):
        super().__init__()
        self.set_engine_evaluator(_FakeEngine(), _FakeEvaluator(func))
        self.engine_params_list = [EngineParams()]


def run_fake(func: Callable, ctx=None):
    """Run ``func(ctx)`` under the evaluation lifecycle; returns the
    FakeEvalResult."""
    from predictionio_tpu.workflow.core_workflow import CoreWorkflow

    return CoreWorkflow.run_evaluation(
        FakeEvaluation(func), [EngineParams()], ctx=ctx
    )
