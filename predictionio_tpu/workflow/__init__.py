"""Workflow layer: train/eval lifecycle orchestration + serving server.

Capability parity with the reference's ``workflow`` package
(core/src/main/scala/io/prediction/workflow/): WorkflowContext (the
SparkContext factory analog — here a mesh + storage handle),
WorkflowParams, CoreWorkflow (train/eval lifecycle + persistence), and
CreateServer (the deployed engine REST server).
"""

from predictionio_tpu.workflow.context import WorkflowContext, workflow_context
from predictionio_tpu.workflow.core_workflow import CoreWorkflow
from predictionio_tpu.workflow.workflow_params import WorkflowParams

__all__ = [
    "CoreWorkflow",
    "WorkflowContext",
    "WorkflowParams",
    "workflow_context",
]
