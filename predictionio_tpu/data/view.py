"""Legacy batch views (deprecated in the reference at 0.9.2, kept for
capability parity).

Reference mapping (data/src/main/scala/io/prediction/data/view/
LBatchView.scala:99-200): ``EventSeq`` — a filterable in-memory event
list with ordered per-entity folds — and ``LBatchView`` — all events of
an app in a time range, with $set/$unset/$delete property aggregation.
New code should use LEventStore / PEventStore (store.py) instead.
"""

from __future__ import annotations

import datetime as _dt
import warnings
from typing import Callable, Dict, List, Optional, Sequence, TypeVar

from predictionio_tpu.data.aggregator import aggregate_properties
from predictionio_tpu.data.event import DataMap, Event, is_special_event
from predictionio_tpu.data.storage import Storage, get_storage

T = TypeVar("T")


class EventSeq:
    """Filterable event list with ordered per-entity folds
    (reference EventSeq :99-130)."""

    def __init__(self, events: Sequence[Event]):
        self.events: List[Event] = list(events)

    def filter(
        self,
        predicate: Optional[Callable[[Event], bool]] = None,
        entity_type: Optional[str] = None,
        event: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
    ) -> "EventSeq":
        def keep(e: Event) -> bool:
            if predicate is not None and not predicate(e):
                return False
            if entity_type is not None and e.entity_type != entity_type:
                return False
            if event is not None and e.event != event:
                return False
            if start_time is not None and e.event_time < start_time:
                return False
            if until_time is not None and e.event_time >= until_time:
                return False
            return True

        return EventSeq([e for e in self.events if keep(e)])

    def aggregate_by_entity_ordered(
        self, init: T, op: Callable[[T, Event], T]
    ) -> Dict[str, T]:
        """Fold each entity's events in event-time order
        (reference :121-127)."""
        by_entity: Dict[str, List[Event]] = {}
        for e in self.events:
            by_entity.setdefault(e.entity_id, []).append(e)
        return {
            entity_id: _fold(sorted(es, key=lambda e: e.event_time), init, op)
            for entity_id, es in by_entity.items()
        }

    def group_by_entity_ordered(
        self, map_fn: Callable[[Event], T]
    ) -> Dict[str, List[T]]:
        by_entity: Dict[str, List[Event]] = {}
        for e in self.events:
            by_entity.setdefault(e.entity_id, []).append(e)
        return {
            entity_id: [
                map_fn(e) for e in sorted(es, key=lambda e: e.event_time)
            ]
            for entity_id, es in by_entity.items()
        }

    def __len__(self) -> int:
        return len(self.events)

    def __iter__(self):
        return iter(self.events)


def _fold(events: List[Event], init: T, op: Callable[[T, Event], T]) -> T:
    acc = init
    for e in events:
        acc = op(acc, e)
    return acc


class LBatchView:
    """All events of an app in a time range (reference LBatchView
    :134-171). Deprecated: use LEventStore/PEventStore."""

    def __init__(
        self,
        app_id: int,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        storage: Optional[Storage] = None,
    ):
        warnings.warn(
            "LBatchView is deprecated; use LEventStore instead",
            DeprecationWarning,
            stacklevel=2,
        )
        self.app_id = app_id
        self.start_time = start_time
        self.until_time = until_time
        self._storage = storage or get_storage()
        self._events: Optional[EventSeq] = None

    @property
    def events(self) -> EventSeq:
        if self._events is None:
            self._events = EventSeq(
                list(
                    self._storage.get_l_events().find(
                        app_id=self.app_id,
                        start_time=self.start_time,
                        until_time=self.until_time,
                    )
                )
            )
        return self._events

    def aggregate_properties(
        self,
        entity_type: str,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
    ) -> Dict[str, DataMap]:
        """$set/$unset/$delete fold per entity (reference :156-171)."""
        filtered = self.events.filter(
            entity_type=entity_type,
            predicate=lambda e: is_special_event(e.event),
            start_time=start_time,
            until_time=until_time,
        )
        return dict(aggregate_properties(filtered))


# PBatchView (the RDD variant, PBatchView.scala:168) collapses into
# LBatchView in the single-controller runtime: both read from the same
# DAO and the columnarization lives in store.PEventStore.find_columns.
PBatchView = LBatchView
