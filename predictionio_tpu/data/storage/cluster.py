"""``cluster`` storage backend — a partitioned, replicated gateway tier.

The reference's production event store is a CLUSTER: HBase regionservers
each own a slice of the key space, and the MD5-prefixed row key
(hbase/HBEventsUtil) exists precisely to spread one app's entities
across regions. This backend plays that role for the gateway tier: N
storage-gateway nodes (api/storage_gateway.py) each own an entity-hash
slice of the event space, and this thin client routes every operation by
the SAME ``crc32(entity_id) % N`` rule the local sqlite shards use
(data/storage/sqlite.py ``shard_index_for``) — one hash rule from a
single file's WAL shards to a multi-host fleet.

Configuration (env registry, data/storage/__init__.py)::

    PIO_STORAGE_SOURCES_C_TYPE=cluster
    PIO_STORAGE_SOURCES_C_NODES=http://n0:7077,http://n1:7077,http://n2:7077
    PIO_STORAGE_SOURCES_C_REPLICAS=2          # R-way replicated writes
    PIO_STORAGE_SOURCES_C_WRITE_QUORUM=1      # min acks per slot
    PIO_STORAGE_SOURCES_C_SECRET=...          # shared gateway secret
    PIO_STORAGE_SOURCES_C_TIMEOUT_S=10        # per-request deadline
    PIO_STORAGE_SOURCES_C_BREAKER_FAILURES=3
    PIO_STORAGE_SOURCES_C_BREAKER_COOLDOWN_S=5
    PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE=C

Semantics (the operator runbook is docs/STORAGE.md):

- **Writes** (insert / insert_batch / bulk columnar import): events are
  assigned their ids CLIENT-side, grouped by home slot ``h =
  crc32(entity_id) % N``, and each slot's slice is written to all R
  replica nodes ``h, h+1, …, h+R-1 (mod N)``. A slot acks once at least
  ``WRITE_QUORUM`` replicas committed it; a replica that failed the
  write while its peers committed is marked STALE (it is missing acked
  data) and leaves the read path until resync. Per-slot failure
  attribution is preserved across routing: ids whose slot missed quorum
  come back in a :class:`PartialBatchError` exactly as a single sqlite
  store reports per-shard slices, and retrying only those slots is
  idempotent because the ids were fixed before the first attempt.

- **Reads/scans**: a read plan assigns every slot to one healthy,
  non-stale replica (primary first). Scatter-gather scans fetch each
  planned node once, filter its rows to the slots it serves in THIS
  plan (a node stores R slots' worth of rows — the filter is what keeps
  replicated rows from double-counting), and feed the per-node batches
  to the shared counting-sort merge (ops/streaming.py). Because every
  entity's rows live wholly on its serving node in per-store scan
  order, the merged wire is BYTE-identical to a single-node store — the
  invariant every storage tier in this repo has held.

- **Failure handling**: transport failures feed a per-node circuit
  breaker; a tripped node leaves the plan (scans re-plan mid-flight
  around a node that dies between planning and fetching), and a
  half-open probe of the node's ``/readyz`` (PR 7's health endpoint)
  closes the breaker when it recovers. A recovered node that missed
  writes is STALE until :meth:`ClusterStorageClient.resync` replays the
  rows above its event-time high-water mark from a peer replica
  (explicit-id re-posts — idempotent REPLACE, the delta-cursor
  contract's destructive-counter machinery then forces the next train
  round to full-rescan rather than trust a cursor over resynced rows).

- **Delta cursors**: a scan's cursor carries the read plan plus every
  planned node's own gateway cursor. Deltas fold while the plan is
  unchanged; any re-plan (node died or recovered between rounds) falls
  back to one full re-scan — never a silently incomplete delta — and
  delta folding resumes on the next round under the new plan.

Fault injection rides the ``le.compact_fault`` idiom: ``faults`` maps
stage names (:data:`FAULT_STAGES`: route_write / quorum_ack /
node_down_scan / resync) to callables tests and the bench use to kill a
node at any boundary and assert zero acked-event loss.
"""

from __future__ import annotations

import datetime as _dt
import http.client as _http_client
import logging
import threading
import time
import zlib
from typing import Any, Dict, Iterator, List, Optional, Sequence

from predictionio_tpu.data.event import Event, new_event_id
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage import http as _http
from predictionio_tpu.data.storage.base import (
    UNSET,
    OptFilter,
    PartialBatchError,
    StorageError,
    StorageSaturatedError,
)
from predictionio_tpu.utils import metrics as _metrics

logger = logging.getLogger(__name__)

PREFIX = "Cluster"

# the named fault-injection boundaries (le.compact_fault idiom):
#   route_write    before a batch's slot slices are dispatched to nodes
#   quorum_ack     after per-replica acks are collected, before the
#                  quorum decision
#   node_down_scan when a scan (re-)plans around an unavailable node
#   resync         before a stale node's catch-up rows are applied
FAULT_STAGES = ("route_write", "quorum_ack", "node_down_scan", "resync")


def _counter(name: str, doc: str, labels=()):
    return _metrics.get_registry().counter(name, doc, labels=labels)


def _gauge(name: str, doc: str, labels=()):
    return _metrics.get_registry().gauge(name, doc, labels=labels)


class _Node:
    """One gateway node: its http client, DAO handles, and the circuit
    breaker + staleness state that governs its read/write eligibility."""

    def __init__(
        self,
        index: int,
        url: str,
        props: Dict[str, str],
        breaker_failures: int,
        breaker_cooldown_s: float,
    ):
        from predictionio_tpu.data.storage import StorageClientConfig

        self.index = index
        self.url = url
        node_props = {"URL": url}
        for key in ("SECRET", "TIMEOUT_S", "RETRIES", "BACKOFF_CAP_S"):
            if props.get(key):
                node_props[key] = props[key]
        # fail fast into the breaker: a dead node must cost one timeout,
        # not the read path's full 4-retry backoff ladder, unless the
        # operator explicitly asked for more
        node_props.setdefault("RETRIES", "1")
        self.client = _http.StorageClient(StorageClientConfig(node_props))
        self.label = f"{self.client.host}:{self.client.port}"
        self._breaker_failures = max(1, breaker_failures)
        self._breaker_cooldown_s = max(0.0, breaker_cooldown_s)
        self._lock = threading.Lock()
        self._consecutive_failures = 0
        self._opened_at: Optional[float] = None
        self.stale = False
        # when this node was marked STALE (monotonic), for the age
        # gauge; None while healthy
        self.stale_since: Optional[float] = None
        self._m_up = _gauge(
            "pio_cluster_node_up",
            "Cluster node breaker state (1 = in the serving path, "
            "0 = breaker open)",
            labels=("node",),
        ).labels(node=self.label)
        self._m_stale = _gauge(
            "pio_cluster_node_stale",
            "Cluster node staleness (1 = missed acked writes; out of "
            "the read path until resync)",
            labels=("node",),
        ).labels(node=self.label)
        # staleness observability (PR 14 follow-up): how LONG a replica
        # has been out of the read path, and how far behind its resync
        # source it was last measured — the two numbers an operator
        # needs to decide between waiting out an auto-resync and paging
        self._m_stale_age = _gauge(
            "pio_cluster_stale_age_seconds",
            "Seconds since this node was marked STALE (0 = healthy); "
            "refreshed on every read-planning pass and status read",
            labels=("node",),
        ).labels(node=self.label)
        self._m_resync_lag = _gauge(
            "pio_cluster_resync_lag_seconds",
            "Event-time gap between a stale node's high-water mark and "
            "its resync source peer, measured at the last resync "
            "attempt (0 = caught up)",
            labels=("node",),
        ).labels(node=self.label)
        self.resync_lag_s = 0.0
        self._m_up.set(1.0)
        self._m_stale.set(0.0)
        self._m_stale_age.set(0.0)
        self._m_resync_lag.set(0.0)

    def le(self, namespace: str) -> "_http.HTTPLEvents":
        return self.client.dao(_http.HTTPLEvents, namespace)

    def dao(self, cls, namespace: str):
        return self.client.dao(cls, namespace)

    # --- circuit breaker ---

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            if (
                self._consecutive_failures >= self._breaker_failures
                and self._opened_at is None
            ):
                self._opened_at = time.monotonic()
                self._m_up.set(0.0)
                logger.warning(
                    "cluster node %s breaker OPEN after %d consecutive "
                    "failures", self.label, self._consecutive_failures,
                )

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            if self._opened_at is not None:
                self._opened_at = None
                self._m_up.set(1.0)
                logger.info("cluster node %s breaker CLOSED", self.label)

    def mark_stale(self) -> None:
        if not self.stale:
            logger.warning(
                "cluster node %s marked STALE (missed an acked write); "
                "out of the read path until resync", self.label,
            )
            self.stale_since = time.monotonic()
        self.stale = True
        self._m_stale.set(1.0)
        self._m_stale_age.set(0.0)

    def clear_stale(self) -> None:
        self.stale = False
        self.stale_since = None
        self.resync_lag_s = 0.0
        self._m_stale.set(0.0)
        self._m_stale_age.set(0.0)
        self._m_resync_lag.set(0.0)

    def stale_age_s(self) -> float:
        """Seconds this node has been STALE (0 while healthy); refreshes
        the ``pio_cluster_stale_age_seconds`` gauge as a side effect, so
        any read-planning pass or status read keeps the exported age
        current for scrapers."""
        age = (
            0.0
            if self.stale_since is None
            else max(0.0, time.monotonic() - self.stale_since)
        )
        self._m_stale_age.set(age)
        return age

    def note_resync_lag(self, lag_s: float) -> None:
        """Record the event-time gap to the resync source measured at
        the latest resync attempt (kept visible across a FAILED replay
        so an operator sees how far behind the node still is)."""
        self.resync_lag_s = max(0.0, float(lag_s))
        self._m_resync_lag.set(self.resync_lag_s)

    def breaker_open(self) -> bool:
        with self._lock:
            return self._opened_at is not None

    def available(self) -> bool:
        """Breaker-gated eligibility. A closed breaker passes without
        I/O; an open one past its cooldown does a half-open ``/readyz``
        probe (the PR 7 health endpoint) and closes on 200."""
        with self._lock:
            if self._opened_at is None:
                return True
            if time.monotonic() - self._opened_at < self._breaker_cooldown_s:
                return False
        if self._probe_ready():
            self.record_success()
            return True
        with self._lock:
            # stay open for another cooldown window
            self._opened_at = time.monotonic()
        return False

    def _probe_ready(self) -> bool:
        conn = None
        try:
            conn = _http_client.HTTPConnection(
                self.client.host, self.client.port,
                timeout=min(2.0, self.client._timeout),
            )
            conn.request("GET", "/readyz")
            resp = conn.getresponse()
            resp.read()
            return resp.status == 200
        except (OSError, _http_client.HTTPException):
            return False
        finally:
            if conn is not None:
                conn.close()

    def close(self) -> None:
        self.client.close()


class StorageClient(base.DAOCacheMixin):
    """Routing client over N gateway nodes (module docstring)."""

    def __init__(self, config=None):
        self.config = config
        props = getattr(config, "properties", None) or {}
        urls = [
            u.strip()
            for u in (props.get("NODES") or props.get("URLS") or "").split(",")
            if u.strip()
        ]
        if not urls:
            raise StorageError(
                "cluster backend needs PIO_STORAGE_SOURCES_<NAME>_NODES="
                "url1,url2,... (one storage gateway per node)"
            )
        breaker_failures = int(props.get("BREAKER_FAILURES", 3) or 3)
        breaker_cooldown_s = float(props.get("BREAKER_COOLDOWN_S", 5) or 5)
        self.nodes: List[_Node] = [
            _Node(i, url, props, breaker_failures, breaker_cooldown_s)
            for i, url in enumerate(urls)
        ]
        self.n_nodes = len(self.nodes)
        self.replicas = max(1, min(int(props.get("REPLICAS", 2) or 2), self.n_nodes))
        self.write_quorum = max(
            1, min(int(props.get("WRITE_QUORUM", 1) or 1), self.replicas)
        )
        self.auto_resync = (props.get("AUTO_RESYNC", "1") or "1") != "0"
        self._init_dao_cache()
        # fault-injection hooks (le.compact_fault idiom)
        self.faults: Dict[str, Any] = {s: None for s in FAULT_STAGES}
        # (app_id, channel_id) pairs this client has touched — the
        # resync enumeration set
        self._known_tables: set = set()
        self._known_lock = threading.Lock()
        self._resync_lock = threading.Lock()
        self._m_writes = _counter(
            "pio_cluster_writes_total",
            "Cluster events by write outcome (acked / under_replicated "
            "= acked with at least one replica missing / failed = "
            "below write quorum)",
            labels=("outcome",),
        )
        self._m_failovers = _counter(
            "pio_cluster_failovers_total",
            "Cluster operations re-planned around an unavailable or "
            "stale node",
            labels=("path",),
        )
        self._m_resyncs = _counter(
            "pio_cluster_resyncs_total",
            "Stale-node resync attempts by outcome",
            labels=("outcome",),
        )
        self._m_resynced = _counter(
            "pio_cluster_resynced_events_total",
            "Events replayed onto stale nodes by resync",
        )
        self._m_degraded = _counter(
            "pio_cluster_degraded_reads_total",
            "Read plans forced to serve a slot from a STALE replica "
            "(every healthier replica unavailable)",
        )

    # --- routing ---

    def slot_of(self, entity_id) -> int:
        """Stable entity→slot hash — the SAME crc32 rule the sqlite
        shard files use, lifted from intra-file to inter-node."""
        return zlib.crc32(str(entity_id).encode("utf-8")) % self.n_nodes

    def replicas_of_slot(self, slot: int) -> List[int]:
        return [
            (slot + r) % self.n_nodes for r in range(self.replicas)
        ]

    def fire(self, stage: str) -> None:
        fault = self.faults.get(stage)
        if fault is not None:
            fault()

    def note_table(self, namespace: str, app_id: int, channel_id) -> None:
        with self._known_lock:
            self._known_tables.add((namespace, app_id, channel_id))

    def known_tables(self) -> List[tuple]:
        with self._known_lock:
            return sorted(
                self._known_tables, key=lambda t: (t[0], t[1], t[2] or -1)
            )

    # --- read planning ---

    def read_plan(self, count_failover: bool = True) -> Dict[int, int]:
        """slot -> node index: primary when eligible, else the first
        available non-stale replica; a stale replica only when nothing
        healthier answers (counted as a degraded read)."""
        if self.auto_resync:
            self.maybe_resync()
        for node in self.nodes:
            # keep the exported stale-age current on every planning
            # pass (a float store per node — off any hot loop)
            node.stale_age_s()
        plan: Dict[int, int] = {}
        failed_over = False
        degraded = False
        for slot in range(self.n_nodes):
            chosen = None
            stale_fallback = None
            for idx in self.replicas_of_slot(slot):
                node = self.nodes[idx]
                if not node.available():
                    continue
                if node.stale:
                    if stale_fallback is None:
                        stale_fallback = idx
                    continue
                chosen = idx
                break
            if chosen is None and stale_fallback is not None:
                chosen = stale_fallback
                degraded = True
            if chosen is None:
                raise StorageError(
                    f"cluster slot {slot} has no available replica "
                    f"(nodes {self.replicas_of_slot(slot)} all down)"
                )
            if chosen != slot:
                failed_over = True
            plan[slot] = chosen
        if failed_over and count_failover:
            self._m_failovers.labels(path="scan").inc()
            self.fire("node_down_scan")
        if degraded:
            self._m_degraded.inc()
        return plan

    def plan_is_degraded(self, plan: Dict[int, int]) -> bool:
        return any(self.nodes[idx].stale for idx in plan.values())

    # --- resync ---

    def maybe_resync(self) -> None:
        """Opportunistic resync of recovered stale nodes, off the
        caller's thread: the replay (peer fetch + re-insert, possibly
        large) runs on a background worker while reads keep planning
        around the still-stale node; it rejoins once the replay lands.
        Non-blocking and single-flight (the lock is held for the
        worker's lifetime)."""
        if not any(
            n.stale and not n.breaker_open() for n in self.nodes
        ):
            return
        if not self._resync_lock.acquire(blocking=False):
            return

        def run():
            try:
                self._resync_locked()
            except Exception:
                logger.exception("background cluster resync failed")
            finally:
                self._resync_lock.release()

        threading.Thread(
            target=run, daemon=True, name="cluster-resync"
        ).start()

    def resync(self, full: bool = False) -> Dict[str, Any]:
        """Replay missed rows onto every recovered stale node from a
        healthy peer replica (module docstring). ``full`` replays each
        table in full instead of above the node's event-time high-water
        mark — the recovery path for out-of-order event times."""
        with self._resync_lock:
            return self._resync_locked(full=full)

    def _resync_locked(self, full: bool = False) -> Dict[str, Any]:
        report: Dict[str, Any] = {"nodes": {}, "events": 0}
        for node in self.nodes:
            if not node.stale:
                continue
            if not node.available():
                report["nodes"][node.label] = "unavailable"
                continue
            try:
                replayed = self._resync_node(node, full=full)
            except (StorageError, OSError) as e:
                logger.warning(
                    "cluster resync of %s failed: %s", node.label, e
                )
                self._m_resyncs.labels(outcome="failed").inc()
                report["nodes"][node.label] = f"failed: {e}"
                continue
            node.clear_stale()
            self._m_resyncs.labels(outcome="completed").inc()
            report["nodes"][node.label] = f"resynced {replayed} events"
            report["events"] += replayed
        return report

    def _resync_node(self, node: _Node, full: bool = False) -> int:
        """Catch one stale node up from its peers: per known table,
        fetch every row at-or-above the node's event-time high-water
        mark (its own store's newest event — the cursor analog of the
        delta contract) from a healthy replica of each slot the node
        participates in, and re-post with the ORIGINAL event ids — an
        idempotent REPLACE on rows the node already has. Deletions are
        reconciled over the same window: a row the node holds that its
        (authoritative) peer no longer has was tombstoned while the
        node was down, and is removed rather than resurrected. Deletes
        of rows OLDER than the high-water mark need ``full=True`` (the
        runbook's recovery path for out-of-order/backfilled data)."""
        self.fire("resync")
        node.note_resync_lag(0.0)  # re-measured below, max across slots
        my_slots = [
            slot
            for slot in range(self.n_nodes)
            if node.index in self.replicas_of_slot(slot)
        ]
        total = 0
        for namespace, app_id, channel_id in self.known_tables():
            le = node.le(namespace)
            le.init(app_id, channel_id)
            hw: Optional[_dt.datetime] = None
            if not full:
                newest = list(
                    le.find(app_id, channel_id, limit=1, reversed=True)
                )
                hw = newest[0].event_time if newest else None
            peer_ids_by_slot: Dict[int, set] = {}
            for slot in my_slots:
                peer = self._peer_for(slot, exclude=node.index)
                if peer is None:
                    raise StorageError(
                        f"no healthy peer replica for slot {slot} to "
                        f"resync {node.label} from"
                    )
                rows = [
                    e
                    for e in peer.le(namespace).find(
                        app_id, channel_id, start_time=hw
                    )
                    if self.slot_of(e.entity_id) == slot
                ]
                peer_ids_by_slot[slot] = {e.event_id for e in rows}
                if rows:
                    # the observability gap this gauge closes: how far
                    # (in EVENT time) the stale node trails its resync
                    # source — recorded before the replay so a failed
                    # attempt still leaves the measured lag visible
                    times = [
                        e.event_time for e in rows
                        if e.event_time is not None
                    ]
                    if times:
                        base = hw if hw is not None else min(times)
                        lag = (max(times) - base).total_seconds()
                        node.note_resync_lag(
                            max(node.resync_lag_s, lag)
                        )
                for s in range(0, len(rows), 500):
                    le.insert_batch(rows[s : s + 500], app_id, channel_id)
                total += len(rows)
            # deletion reconciliation: anything the node holds in the
            # window that the peer does not is a missed tombstone
            for e in le.find(app_id, channel_id, start_time=hw):
                slot = self.slot_of(e.entity_id)
                peer_ids = peer_ids_by_slot.get(slot)
                if peer_ids is not None and e.event_id not in peer_ids:
                    le.delete(e.event_id, app_id, channel_id)
                    total += 1
        self._m_resynced.inc(total)
        return total

    def replan_slots(
        self, slots, exclude_idx: int, failed: set
    ) -> "tuple[Dict[int, set], bool]":
        """Move ``slots`` off a failed node onto their next available
        NON-STALE replica, excluding every node that already failed this
        scatter (the ping-pong guard); a stale replica (missing acked
        rows) is a last resort only. Returns ``(moved, used_stale)`` —
        ``used_stale`` tells the caller some slot is now served by a
        replica that may be incomplete, so the scan must not label a
        cache artifact or chain a delta. Raises when a slot has no
        replica left — the shared re-plan step of every scatter path."""
        moved: Dict[int, set] = {}
        used_stale = False
        for slot in slots:
            nxt = None
            stale_fallback = None
            for idx in self.replicas_of_slot(slot):
                if idx == exclude_idx or idx in failed:
                    continue
                if not self.nodes[idx].available():
                    continue
                if self.nodes[idx].stale:
                    if stale_fallback is None:
                        stale_fallback = idx
                    continue
                nxt = idx
                break
            if nxt is None and stale_fallback is not None:
                nxt = stale_fallback
                used_stale = True
            if nxt is None:
                raise StorageError(
                    f"cluster slot {slot} lost its last replica mid-scan"
                )
            moved.setdefault(nxt, set()).add(slot)
        if used_stale:
            self._m_degraded.inc()
        return moved, used_stale

    def _peer_for(self, slot: int, exclude: int) -> Optional[_Node]:
        for idx in self.replicas_of_slot(slot):
            if idx == exclude:
                continue
            node = self.nodes[idx]
            if node.available() and not node.stale:
                return node
        return None

    # --- status (CLI / pio top feed) ---

    def status(self) -> List[Dict[str, Any]]:
        out = []
        for node in self.nodes:
            out.append(
                {
                    "index": node.index,
                    "url": node.url,
                    "available": node.available(),
                    "breaker_open": node.breaker_open(),
                    "stale": node.stale,
                    "stale_age_s": node.stale_age_s(),
                    "resync_lag_s": node.resync_lag_s,
                    "primary_slot": node.index,
                    "replica_slots": [
                        s
                        for s in range(self.n_nodes)
                        if node.index in self.replicas_of_slot(s)
                    ],
                }
            )
        return out

    def close(self) -> None:
        for node in self.nodes:
            node.close()


class ClusterLEvents(base.LEvents):
    """Event DAO over the routed node fleet (module docstring)."""

    def __init__(self, client: StorageClient, config=None, namespace: str = ""):
        self._c = client
        self.namespace = namespace or "pio"

    def _le(self, node: _Node) -> "_http.HTTPLEvents":
        return node.le(self.namespace)

    # --- lifecycle (broadcast: every node may own any app's slice) ---

    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        errors = []
        for node in self._c.nodes:
            try:
                self._le(node).init(app_id, channel_id)
                node.record_success()
            except (StorageError, OSError) as e:
                node.record_failure()
                errors.append((node.label, e))
        if errors:
            # init is an admin op: partial table creation would hide a
            # node's slice later — require the whole fleet
            raise StorageError(
                f"cluster init(app {app_id}) failed on {errors!r}"
            )
        self._c.note_table(self.namespace, app_id, channel_id)
        return True

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        errors = []
        found = False
        for node in self._c.nodes:
            try:
                found = self._le(node).remove(app_id, channel_id) or found
                node.record_success()
            except (StorageError, OSError) as e:
                node.record_failure()
                errors.append((node.label, e))
        if errors:
            # a node that missed a remove would resurrect dropped rows:
            # surface loudly, the operator retries once it is back
            raise StorageError(
                f"cluster remove(app {app_id}) failed on {errors!r}; "
                "retry once every node is reachable"
            )
        return found

    def close(self) -> None:
        self._c.close()

    # --- writes ---

    def insert(self, event: Event, app_id: int, channel_id: Optional[int] = None) -> str:
        return self.insert_batch([event], app_id, channel_id)[0]

    def write(
        self, events, app_id: int, channel_id: Optional[int] = None
    ) -> List[str]:
        return self.insert_batch(list(events), app_id, channel_id)

    def insert_batch(
        self,
        events: Sequence[Event],
        app_id: int,
        channel_id: Optional[int] = None,
    ) -> List[str]:
        """R-way replicated batch write with per-slot quorum ack
        (module docstring). Ids are fixed client-side BEFORE the first
        attempt, so retrying the failed slots of a
        :class:`PartialBatchError` is idempotent on every replica that
        already committed them (explicit-id re-post = REPLACE)."""
        events = list(events)
        if not events:
            return []
        self._c.note_table(self.namespace, app_id, channel_id)
        fixed = []
        for e in events:
            eid = e.event_id or new_event_id()
            fixed.append(e if e.event_id else e.with_event_id(eid))
        eids = [e.event_id for e in fixed]
        # group by home slot, preserving input order within each slice
        by_slot: Dict[int, List[Event]] = {}
        for e in fixed:
            by_slot.setdefault(self._c.slot_of(e.entity_id), []).append(e)
        self._c.fire("route_write")
        acks: Dict[str, int] = {eid: 0 for eid in eids}
        # per slot: (slice ids, [(node, committed ids or None, was it
        # saturation)]) — stale marking is decided AFTER the quorum
        # outcome is known, so a replica is only ever marked stale for
        # missing data that actually ACKED (marking on a total slot
        # failure could stale-out every node at once and leave resync
        # with no healthy peer to replay from)
        outcomes: Dict[int, tuple] = {}
        # largest backoff hint any replica attached to a capacity
        # refusal — propagated outward so clients honor the actual
        # saturated store's window, not a made-up one
        retry_hint: Optional[float] = None
        for slot, slice_events in by_slot.items():
            slice_ids = [e.event_id for e in slice_events]
            results = []
            for idx in self._c.replicas_of_slot(slot):
                node = self._c.nodes[idx]
                if not node.available():
                    # known-down replica: degraded write, hard miss
                    results.append((node, None, False))
                    continue
                saturated = False
                try:
                    self._le(node).insert_batch(
                        slice_events, app_id, channel_id
                    )
                    node.record_success()
                    committed = frozenset(slice_ids)
                except PartialBatchError as pe:
                    node.record_success()  # the node answered
                    committed = frozenset(
                        eid for eid in slice_ids
                        if eid not in pe.failed_ids
                    )
                    # a capacity-attributed partial slice is
                    # saturation, not node death: keep the backoff
                    # contract intact through the routing layer
                    if pe.retry_after_s is not None:
                        saturated = True
                        retry_hint = max(
                            retry_hint or 0.0, pe.retry_after_s
                        )
                except StorageSaturatedError as se:
                    # alive but at capacity: breaker stays shut, peers
                    # may still ack
                    node.record_success()
                    retry_hint = max(
                        retry_hint or 0.0, se.retry_after_s
                    )
                    results.append((node, None, True))
                    continue
                except (StorageError, OSError) as e:
                    node.record_failure()
                    results.append((node, None, False))
                    logger.warning(
                        "cluster write slice (slot %d) failed on %s: %s",
                        slot, node.label, e,
                    )
                    continue
                for eid in committed:
                    acks[eid] += 1
                results.append((node, committed, saturated))
            outcomes[slot] = (slice_ids, results)
        self._c.fire("quorum_ack")
        failed = frozenset(
            eid for eid in eids if acks[eid] < self._c.write_quorum
        )
        # stale = this replica is missing an event that IS acked (its
        # peers made the write durable without it); a slot that failed
        # outright left no replica behind, so nobody is stale for it
        any_hard_miss = False
        for slot, (slice_ids, results) in outcomes.items():
            acked_ids = {
                eid for eid in slice_ids if eid not in failed
            }
            for node, committed, saturated in results:
                if committed is None or acked_ids - committed:
                    if acked_ids:
                        node.mark_stale()
                    if not saturated:
                        any_hard_miss = True
        n_acked = len(eids) - len(failed)
        self._c._m_writes.labels(outcome="acked").inc(n_acked)
        self._c._m_writes.labels(outcome="failed").inc(len(failed))
        under = sum(
            1
            for eid in eids
            if eid not in failed and acks[eid] < self._c.replicas
        )
        if under:
            self._c._m_writes.labels(outcome="under_replicated").inc(under)
        if failed:
            # whole-batch saturation may only be claimed when NO
            # replica committed anything: a below-quorum commit is
            # still durable somewhere, and a caller retrying "the whole
            # batch" with fresh auto ids would duplicate those rows
            any_commit = any(
                committed
                for _, results in outcomes.values()
                for _, committed, _ in results
            )
            if n_acked == 0 and not any_hard_miss and not any_commit:
                raise StorageSaturatedError(
                    "every replica refused the batch at capacity; "
                    "retry after backoff",
                    retry_after_s=retry_hint or 1.0,
                )
            raise PartialBatchError(
                f"{len(failed)} of {len(eids)} events missed the write "
                f"quorum ({self._c.write_quorum})",
                event_ids=eids,
                failed_ids=failed,
                # all-saturation failures are retryable after backoff,
                # honoring the saturated replicas' own hint
                retry_after_s=(
                    (retry_hint or 1.0) if not any_hard_miss else None
                ),
            )
        return eids

    # --- point reads / deletes ---

    def get(
        self, event_id: str, app_id: int, channel_id: Optional[int] = None
    ) -> Optional[Event]:
        # the id does not carry the entity hash: ask available nodes in
        # order (each probe is cheap; replicas make the first hit fast)
        candidates = self._order_all_available()
        if not candidates:
            raise StorageError("cluster get: no node available")
        last: Optional[Exception] = None
        answered: set = set()  # node indices that answered (non-stale)
        stale_hit: Optional[Event] = None
        for node in candidates:
            try:
                out = self._le(node).get(event_id, app_id, channel_id)
                node.record_success()
                if not node.stale:
                    answered.add(node.index)
                    if out is not None:
                        return out
                elif out is not None and stale_hit is None:
                    # a STALE replica's positive answer may be a row
                    # whose tombstone it missed: judged below against
                    # the healthy replicas of its slot, never returned
                    # outright (serving it could resurrect a delete)
                    stale_hit = out
            except (StorageError, OSError) as e:
                node.record_failure()
                last = e
        if not answered:
            raise StorageError(f"cluster get failed on every node: {last}")
        # an acked row lives on >= WRITE_QUORUM replicas of its slot,
        # so once R - W + 1 of them deny it no quorum-committed copy
        # can be hiding (pigeonhole) — the shared bar for both
        # judgments below
        need = self._c.replicas - self._c.write_quorum + 1
        if stale_hit is not None:
            slot = self._c.slot_of(stale_hit.entity_id)
            got = sum(
                1
                for idx in self._c.replicas_of_slot(slot)
                if idx in answered
            )
            if got >= need:
                # enough healthy replicas deny the row: the stale copy
                # is a missed tombstone (or never acked) — not found
                return None
            raise StorageError(
                f"cluster get({event_id}): found only on a stale "
                f"replica with {got}/{need} healthy replicas of slot "
                f"{slot} answering — cannot tell a missed tombstone "
                "from an under-replicated row until resync completes"
            )
        # "not found" is only definitive when, for EVERY slot the event
        # could live in, enough of the slot's replicas answered that any
        # quorum-sized committed set must intersect them — otherwise the
        # row may exist on an unreachable (or stale) replica, and
        # unavailability must not masquerade as nonexistence
        for slot in range(self._c.n_nodes):
            got = sum(
                1
                for idx in self._c.replicas_of_slot(slot)
                if idx in answered
            )
            if got < need:
                raise StorageError(
                    f"cluster get({event_id}): not found on answering "
                    f"nodes, but only {got}/{need} required replicas of "
                    f"slot {slot} answered — the event may exist on an "
                    f"unreachable replica: {last}"
                )
        return None

    def delete(
        self, event_id: str, app_id: int, channel_id: Optional[int] = None
    ) -> bool:
        found = False
        missed: List[_Node] = []
        deleters: List[int] = []  # node indices that held + removed it
        for node in self._c.nodes:
            if not node.available():
                missed.append(node)
                continue
            try:
                if self._le(node).delete(event_id, app_id, channel_id):
                    found = True
                    deleters.append(node.index)
                node.record_success()
            except (StorageError, OSError):
                node.record_failure()
                missed.append(node)
        if found:
            # a replica that missed the tombstone while a peer removed
            # the row may still hold it: stale until resync reconciles
            # (a no-op delete stales nobody — there was nothing to
            # miss). The id carries no entity hash, but every node
            # that held the row is a replica of its (unknown) slot, so
            # intersecting the deleters' candidate-slot windows pins
            # the row's replica set with zero extra round trips — a
            # tombstone miss then stales only nodes that could
            # actually hold the row (exact once every live replica
            # answered), not the whole fleet. An empty intersection
            # (impossible for slot-routed rows) falls back to staling
            # every missed node rather than risk resurrecting it.
            cand: Optional[set] = None
            for j in deleters:
                window = {
                    (j - r) % self._c.n_nodes
                    for r in range(self._c.replicas)
                }
                cand = window if cand is None else (cand & window)
            eligible = {
                idx
                for s in (cand or set())
                for idx in self._c.replicas_of_slot(s)
            }
            for node in missed:
                if not eligible or node.index in eligible:
                    node.mark_stale()
        return found

    def _order_all_available(self) -> List[_Node]:
        nodes = [
            n for n in self._c.nodes if n.available() and not n.stale
        ]
        nodes += [n for n in self._c.nodes if n.available() and n.stale]
        return nodes

    # --- find / aggregate (scatter-gather with slot filtering) ---

    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: OptFilter = UNSET,
        target_entity_id: OptFilter = UNSET,
        limit: Optional[int] = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        kwargs = dict(
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
        )
        if entity_id is not None:
            # single-entity: route straight to its replica set
            slot = self._c.slot_of(entity_id)
            out = self._slot_read(
                slot,
                lambda le: list(
                    le.find(
                        app_id, channel_id, limit=limit,
                        reversed=reversed, **kwargs,
                    )
                ),
            )
            return iter(out)
        plan = self._c.read_plan()
        accept = _slots_by_node(plan)
        merged: List[Event] = []

        def fetch(node: _Node, slots: set) -> None:
            rows = list(self._le(node).find(app_id, channel_id, **kwargs))
            merged.extend(
                e for e in rows if self._c.slot_of(e.entity_id) in slots
            )

        self._scatter_fetch(accept, fetch)
        merged.sort(key=lambda e: e.event_time, reverse=reversed)
        if limit is not None and limit >= 0:
            merged = merged[:limit]
        return iter(merged)

    def aggregate_properties(
        self,
        app_id: int,
        entity_type: str,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        required: Optional[Sequence[str]] = None,
    ) -> Dict[str, "PropertyMap"]:
        plan = self._c.read_plan()
        accept = _slots_by_node(plan)
        out: Dict[str, Any] = {}

        def fetch(node: _Node, slots: set) -> None:
            part = self._le(node).aggregate_properties(
                app_id, entity_type, channel_id=channel_id,
                start_time=start_time, until_time=until_time,
                required=required,
            )
            # entity→slot is a function, so per-slot key sets are
            # disjoint: the filtered merge cannot collide
            out.update(
                {
                    k: v
                    for k, v in part.items()
                    if self._c.slot_of(k) in slots
                }
            )

        self._scatter_fetch(accept, fetch)
        return out

    def aggregate_properties_of_entity(
        self,
        app_id: int,
        entity_type: str,
        entity_id: str,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
    ):
        slot = self._c.slot_of(entity_id)
        return self._slot_read(
            slot,
            lambda le: le.aggregate_properties_of_entity(
                app_id, entity_type, entity_id, channel_id=channel_id,
                start_time=start_time, until_time=until_time,
            ),
        )

    def _slot_read(self, slot: int, fn):
        """Run a read against one slot's replicas with failover."""
        last: Optional[Exception] = None
        candidates = self._c.replicas_of_slot(slot)
        ordered = sorted(
            candidates,
            key=lambda idx: (
                not self._c.nodes[idx].available(),
                self._c.nodes[idx].stale,
                candidates.index(idx),
            ),
        )
        for pos, idx in enumerate(ordered):
            node = self._c.nodes[idx]
            if not node.available():
                continue
            try:
                out = fn(self._le(node))
                node.record_success()
                if pos > 0:
                    self._c._m_failovers.labels(path="read").inc()
                return out
            except (StorageError, OSError) as e:
                node.record_failure()
                last = e
        raise StorageError(
            f"cluster slot {slot} read failed on every replica: {last}"
        )

    def _node_read(self, node: _Node, fn):
        try:
            out = fn(self._le(node))
            node.record_success()
            return out
        except (StorageError, OSError):
            node.record_failure()
            raise

    def _scatter_fetch(self, accept: Dict[int, set], fetch) -> None:
        """Run ``fetch(node, slots)`` for every planned assignment,
        re-planning mid-scatter around a node that dies between
        planning and its fetch: its slots move to their next available
        replica that has not ALSO failed this scatter (which may mean
        re-fetching an already-visited node for JUST those slots)."""
        pending = [
            (idx, set(slots)) for idx, slots in sorted(accept.items())
        ]
        failed: set = set()
        while pending:
            node_idx, slots = pending.pop(0)
            node = self._c.nodes[node_idx]
            try:
                if not node.available():
                    raise _NodeUnavailable(node.label)
                fetch(node, slots)
                node.record_success()
                continue
            except _NodeUnavailable:
                pass  # known-down: no extra breaker feedback needed
            except (StorageError, OSError) as e:
                node.record_failure()
                logger.warning(
                    "cluster scatter re-planning around %s: %s",
                    node.label, e,
                )
            failed.add(node_idx)
            self._c.fire("node_down_scan")
            self._c._m_failovers.labels(path="scan").inc()
            moved, _ = self._c.replan_slots(slots, node_idx, failed)
            pending.extend(sorted(moved.items()))

    # --- columnar writes ---

    def insert_columns(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        event: str,
        entity_type: str,
        target_entity_type: str,
        entity_ids,
        target_ids,
        values,
        value_property: str = "rating",
        event_time: Optional[_dt.datetime] = None,
        event_times_ms=None,
    ) -> int:
        from predictionio_tpu.data.storage import columnar as col

        e_names, e_codes = col.encode_strings(entity_ids)
        g_names, g_codes = col.encode_strings(target_ids)
        return self.insert_columns_encoded(
            app_id, channel_id, event=event, entity_type=entity_type,
            target_entity_type=target_entity_type,
            entity_names=e_names, entity_codes=e_codes,
            target_names=g_names, target_codes=g_codes,
            values=values, value_property=value_property,
            event_time=event_time, event_times_ms=event_times_ms,
        )

    def insert_columns_encoded(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        event: str,
        entity_type: str,
        target_entity_type: str,
        entity_names,
        entity_codes,
        target_names,
        target_codes,
        values,
        value_property: str = "rating",
        event_time: Optional[_dt.datetime] = None,
        event_times_ms=None,
    ) -> int:
        """Bulk import, partitioned by entity slot: each slot's row
        subset (with subset dictionaries) goes to its replica set. A
        slot with zero committed replicas fails the import loudly —
        bulk import has no per-row retry contract."""
        import numpy as np

        self._c.note_table(self.namespace, app_id, channel_id)
        e_codes = np.asarray(entity_codes, np.int64)
        g_codes = np.asarray(target_codes, np.int64)
        vals = np.asarray(values, np.float32)
        times = (
            None if event_times_ms is None
            else np.asarray(event_times_ms, np.int64)
        )
        e_names_arr = np.asarray(entity_names, object)
        g_names_arr = np.asarray(target_names, object)
        name_slots = np.fromiter(
            (self._c.slot_of(n) for n in e_names_arr),
            np.int64, count=len(e_names_arr),
        )
        row_slots = name_slots[e_codes]
        self._c.fire("route_write")
        total = 0
        for slot in np.unique(row_slots):
            sel = row_slots == slot
            se, se_codes = np.unique(e_codes[sel], return_inverse=True)
            sg, sg_codes = np.unique(g_codes[sel], return_inverse=True)
            slice_kwargs = dict(
                event=event,
                entity_type=entity_type,
                target_entity_type=target_entity_type,
                entity_names=e_names_arr[se],
                entity_codes=se_codes.astype(np.int32),
                target_names=g_names_arr[sg],
                target_codes=sg_codes.astype(np.int32),
                values=vals[sel],
                value_property=value_property,
                event_time=event_time,
                event_times_ms=None if times is None else times[sel],
            )
            acked = 0
            missed: List[_Node] = []
            for idx in self._c.replicas_of_slot(int(slot)):
                node = self._c.nodes[idx]
                if not node.available():
                    missed.append(node)
                    continue
                try:
                    self._le(node).insert_columns_encoded(
                        app_id, channel_id, **slice_kwargs
                    )
                    node.record_success()
                    acked += 1
                except StorageSaturatedError:
                    # backpressure, not node death: the breaker stays
                    # shut and the node is only stale if peers commit
                    node.record_success()
                    missed.append(node)
                except (StorageError, OSError) as e:
                    node.record_failure()
                    missed.append(node)
                    logger.warning(
                        "cluster columnar import slot %d failed on %s: "
                        "%s", int(slot), node.label, e,
                    )
            self._c.fire("quorum_ack")
            if acked < self._c.write_quorum:
                raise StorageError(
                    f"cluster columnar import: slot {int(slot)} missed "
                    f"the write quorum ({acked}/{self._c.write_quorum})"
                )
            # stale only when the slice actually acked elsewhere — a
            # replica can only "miss" data that became durable
            for node in missed:
                node.mark_stale()
            total += int(sel.sum())
        return total

    # --- columnar scans (scatter-gather, shared code space) ---

    def find_columns_native(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        value_spec=None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        target_entity_type: OptFilter = UNSET,
        event_names: Optional[Sequence[str]] = None,
    ):
        import numpy as np

        from predictionio_tpu.data.storage.columnar import ColumnarEvents

        stream = self.stream_columns_native(
            app_id, channel_id, value_spec=value_spec,
            start_time=start_time, until_time=until_time,
            entity_type=entity_type,
            target_entity_type=target_entity_type,
            event_names=event_names,
        )
        e_parts, t_parts, v_parts = [], [], []
        for e, t, v in stream:
            e_parts.append(np.asarray(e, np.int64))
            t_parts.append(np.asarray(t, np.int64))
            v_parts.append(np.asarray(v, np.float32))
        names = np.asarray(stream.names, object)
        if not v_parts:
            return ColumnarEvents.empty()
        e_codes = np.concatenate(e_parts)
        t_codes = np.concatenate(t_parts)
        e_uniq, e_inv = np.unique(e_codes, return_inverse=True)
        t_uniq, t_inv = np.unique(t_codes, return_inverse=True)
        return ColumnarEvents(
            entity_names=names[e_uniq],
            target_names=names[t_uniq],
            entity_codes=e_inv.astype(np.int32),
            target_codes=t_inv.astype(np.int32),
            values=np.concatenate(v_parts),
        )

    def stream_columns_native(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        value_spec=None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        target_entity_type: OptFilter = UNSET,
        event_names: Optional[Sequence[str]] = None,
        batch_rows: int = 1_048_576,
    ):
        """Scatter-gather chunked scan: one batch per planned node,
        slot-filtered and re-encoded into one shared code space, feeding
        the counting-sort merge a wire BYTE-identical to a single-node
        store (module docstring). The stream's cursor carries the plan
        plus every node's own cursor; its fingerprint combines every
        node's pre-scan fingerprint. A degraded plan (stale replica
        serving) disables both — a scan that may be missing acked rows
        must never label a cache artifact or chain a delta."""
        plan = self._c.read_plan()
        return self._scatter_stream(
            plan, app_id, channel_id,
            value_spec=value_spec, start_time=start_time,
            until_time=until_time, entity_type=entity_type,
            target_entity_type=target_entity_type,
            event_names=event_names, batch_rows=batch_rows,
            delta_cursors=None,
        )

    def stream_columns_delta(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        cursor: tuple,
        value_spec=None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        target_entity_type: OptFilter = UNSET,
        event_names: Optional[Sequence[str]] = None,
        batch_rows: int = 1_048_576,
    ):
        """Per-node delta scan. Valid only while the read plan is
        UNCHANGED since the cursor (same topology, same slot→node
        assignment): any re-plan — a node died, recovered, or was
        resynced between rounds — returns None so the caller does one
        full re-scan under the new plan instead of trusting a cursor
        whose per-slot coverage no longer matches the folded prefix.
        Continuous training therefore keeps folding deltas across a
        node outage with exactly two full-rescan rounds: the one that
        first routes around the dead node, and the one that routes back
        after resync."""
        if (
            not isinstance(cursor, tuple)
            or len(cursor) != 5
            or cursor[0] != "cluster-delta"
        ):
            return None
        _, n_nodes, replicas, plan_then, node_cursors = cursor
        if n_nodes != self._c.n_nodes or replicas != self._c.replicas:
            return None  # topology changed under the cursor
        plan = self._c.read_plan()
        if tuple(sorted(plan.items())) != plan_then:
            return None  # re-planned: full rescan owns correctness
        cursors = dict(node_cursors)
        if set(cursors) != set(plan.values()) or any(
            cursors[idx] is None for idx in cursors
        ):
            return None
        return self._scatter_stream(
            plan, app_id, channel_id,
            value_spec=value_spec, start_time=start_time,
            until_time=until_time, entity_type=entity_type,
            target_entity_type=target_entity_type,
            event_names=event_names, batch_rows=batch_rows,
            delta_cursors=cursors,
        )

    def _scatter_stream(
        self,
        plan: Dict[int, int],
        app_id: int,
        channel_id,
        *,
        value_spec,
        start_time,
        until_time,
        entity_type,
        target_entity_type,
        event_names,
        batch_rows,
        delta_cursors: Optional[Dict[int, tuple]],
    ):
        import numpy as np

        from predictionio_tpu.data.storage.columnar import ColumnarStream

        accept = _slots_by_node(plan)
        degraded = self._c.plan_is_degraded(plan)
        scan_kwargs = dict(
            value_spec=value_spec, start_time=start_time,
            until_time=until_time, entity_type=entity_type,
            target_entity_type=target_entity_type,
            event_names=event_names, batch_rows=batch_rows,
        )
        # pre-scan fingerprints, so a cached artifact can never be
        # labeled newer than its data (the ColumnarStream contract)
        fingerprint: Optional[tuple] = None
        if not degraded:
            fps = []
            try:
                for node_idx in sorted(accept):
                    fp = self._node_read(
                        self._c.nodes[node_idx],
                        lambda le: le.store_fingerprint(
                            app_id, channel_id
                        ),
                    )
                    if fp is None:
                        fps = None
                        break
                    fps.append((node_idx, fp))
            except (StorageError, OSError):
                fps = None
            if fps is not None:
                fingerprint = (
                    "cluster",
                    tuple(sorted(plan.items())),
                    tuple(fps),
                )

        # one shared code space across node batches: the same item id
        # appears on EVERY node that stores one of its raters' slots,
        # so per-node dictionaries must be unified, not concatenated
        global_codes: Dict[str, int] = {}
        names_list: List[str] = []
        box: Dict[str, Any] = {
            "cursors": {}, "complete": False, "invalid": False,
        }
        # filled with the ColumnarStream below, so batches() can strip
        # its fingerprint if a mid-scan failover degrades coverage
        holder: Dict[str, Any] = {}
        c = self._c
        get_le = self._le

        def remap(local_names: "np.ndarray", codes: "np.ndarray"):
            lut = np.empty(len(local_names), np.int64)
            for j, name in enumerate(local_names):
                key = str(name)
                code = global_codes.get(key)
                if code is None:
                    code = len(names_list)
                    global_codes[key] = code
                    names_list.append(key)
                lut[j] = code
            return lut[codes]

        def fetch_node(node: _Node, slots: set):
            """One node's scan, materialized + slot-filtered. Returns
            the (e, t, v) batch or None (nothing to emit); raises
            _DeltaInvalid when the node declines its delta."""
            le = get_le(node)
            if delta_cursors is not None:
                stream = le.stream_columns_delta(
                    app_id, channel_id,
                    cursor=delta_cursors[node.index], **scan_kwargs,
                )
                if stream is None:
                    raise _DeltaInvalid(node.label)
            else:
                stream = le.stream_columns_native(
                    app_id, channel_id, **scan_kwargs
                )
            if stream is None:
                # no chunked path on this node (old gateway): one-batch
                # fallback, losing cursor support for this round
                cols = le.find_columns_native(
                    app_id, channel_id,
                    **{
                        k: v
                        for k, v in scan_kwargs.items()
                        if k != "batch_rows"
                    },
                )
                if cols is None:
                    box["cursors"][node.index] = None
                    return None
                stream = ColumnarStream.from_columnar(cols)
            e_parts, t_parts, v_parts = [], [], []
            for e, t, v in stream:
                e_parts.append(np.asarray(e, np.int64))
                t_parts.append(np.asarray(t, np.int64))
                v_parts.append(np.asarray(v, np.float32))
            local_names = np.asarray(stream.names, object)
            box["cursors"][node.index] = stream.cursor
            if not v_parts:
                return None
            e_codes = np.concatenate(e_parts)
            t_codes = np.concatenate(t_parts)
            values = np.concatenate(v_parts)
            # slot filter: keep only rows whose entity this node SERVES
            # in the current plan (it also stores up to R-1 other
            # slots' replica rows — the filter is the dedup)
            name_slots = np.fromiter(
                (c.slot_of(n) for n in local_names),
                np.int64, count=len(local_names),
            )
            slot_ok = np.zeros(c.n_nodes, bool)
            slot_ok[list(slots)] = True
            keep = slot_ok[name_slots[e_codes]]
            if not keep.any():
                return None
            return (
                remap(local_names, e_codes[keep]),
                remap(local_names, t_codes[keep]),
                values[keep],
            )

        def batches():
            pending = [
                (idx, set(slots)) for idx, slots in sorted(accept.items())
            ]
            failed: set = set()
            while pending:
                node_idx, slots = pending.pop(0)
                node = c.nodes[node_idx]
                try:
                    if not node.available():
                        raise _NodeUnavailable(node.label)
                    batch = fetch_node(node, slots)
                    node.record_success()
                except _DeltaInvalid:
                    # a node declined its delta: the WHOLE cluster scan
                    # falls back to a full repack (cursor() → None);
                    # stop early, nothing more to gain this round
                    box["invalid"] = True
                    return
                except (StorageError, OSError) as e:
                    if not isinstance(e, _NodeUnavailable):
                        node.record_failure()
                    if delta_cursors is not None:
                        # mid-delta failover changes the plan: fall back
                        box["invalid"] = True
                        return
                    # mid-scan failover: the node died between planning
                    # and its fetch — move its slots to their next
                    # available replica that has not also failed this
                    # scan (possibly re-fetching a node already
                    # visited, filtered to JUST these slots)
                    c.fire("node_down_scan")
                    c._m_failovers.labels(path="scan").inc()
                    logger.warning(
                        "cluster scan re-planning around %s: %s",
                        node.label, e,
                    )
                    failed.add(node_idx)
                    moved, used_stale = c.replan_slots(
                        slots, node_idx, failed
                    )
                    pending.extend(sorted(moved.items()))
                    # a failover scan's coverage no longer matches the
                    # planned cursor set: serve the data, skip the cursor
                    box["invalid"] = True
                    if used_stale:
                        # slots now served by a STALE replica: the scan
                        # may be missing acked rows, so the pre-scan
                        # fingerprint must not survive to label a cache
                        # artifact as complete
                        stream = holder.get("stream")
                        if stream is not None:
                            stream.fingerprint = None
                    continue
                if batch is not None:
                    yield batch
            box["complete"] = True

        def names():
            out = np.empty(len(names_list), object)
            out[:] = names_list
            return out

        def cursor():
            # no cursor from a degraded plan (possibly missing acked
            # rows), an incomplete/re-planned iteration, or any node
            # that could not vouch for its own scan
            if degraded or box["invalid"] or not box["complete"]:
                return None
            cursors = box["cursors"]
            if set(cursors) != set(accept) or any(
                cursors[idx] is None for idx in cursors
            ):
                return None
            return (
                "cluster-delta",
                c.n_nodes,
                c.replicas,
                tuple(sorted(plan.items())),
                tuple(sorted(cursors.items())),
            )

        out = ColumnarStream(
            batches(), names,
            fingerprint=None if degraded else fingerprint,
            cursor_fn=cursor,
        )
        holder["stream"] = out
        return out

    def store_fingerprint(
        self, app_id: int, channel_id: Optional[int] = None
    ) -> Optional[tuple]:
        plan = self._c.read_plan(count_failover=False)
        if self._c.plan_is_degraded(plan):
            return None
        fps = []
        for node_idx in sorted(set(plan.values())):
            try:
                fp = self._node_read(
                    self._c.nodes[node_idx],
                    lambda le: le.store_fingerprint(app_id, channel_id),
                )
            except (StorageError, OSError):
                return None
            if fp is None:
                return None
            fps.append((node_idx, fp))
        return ("cluster", tuple(sorted(plan.items())), tuple(fps))


class _DeltaInvalid(StorageError):
    """A node declined its delta mid-scatter — the cluster stream turns
    this into a full-repack fallback at the caller."""


class _NodeUnavailable(StorageError):
    """A planned node's breaker is open at fetch time — re-plan without
    feeding the breaker another failure."""


def _slots_by_node(plan: Dict[int, int]) -> Dict[int, set]:
    accept: Dict[int, set] = {}
    for slot, node_idx in plan.items():
        accept.setdefault(node_idx, set()).add(slot)
    return accept


# --- metadata DAOs: broadcast writes, first-healthy reads ---
#
# Metadata (apps, keys, channels, instances, models) is tiny and rarely
# written; the cluster replicates it to EVERY node so any gateway can
# resolve an access key or an app id with the rest of the fleet dark.
# Ids/keys are fixed client-side (or taken from the first node) before
# replication, so the copies agree. A node that misses a metadata write
# (down at the time) is marked stale; metadata is NOT covered by the
# event-tier resync — the runbook (docs/STORAGE.md) says to re-run the
# admin command once the fleet is whole, which is idempotent here.


class _ClusterMetaBase:
    DAO_CLS: type = None  # the HTTP* DAO proxied per node

    def __init__(self, client: StorageClient, config=None, namespace: str = ""):
        self._c = client
        self.namespace = namespace or "pio"

    def _dao(self, node: _Node):
        return node.dao(self.DAO_CLS, self.namespace)

    def _read(self, fn):
        last: Optional[Exception] = None
        for node in self._c.nodes:
            if not node.available():
                continue
            try:
                out = fn(self._dao(node))
                node.record_success()
                return out
            except (StorageError, OSError) as e:
                node.record_failure()
                last = e
        raise StorageError(
            f"cluster metadata read failed on every node: {last}"
        )

    def _broadcast(self, fn, primary_first: bool = False):
        """Apply a write on every available node; returns the primary
        (first successful) result. At least one node must succeed; the
        rest are best-effort (a skipped node is marked stale)."""
        results = []
        errors = []
        for node in self._c.nodes:
            if not node.available():
                node.mark_stale()
                continue
            try:
                results.append(fn(self._dao(node)))
                node.record_success()
                if primary_first and len(results) == 1:
                    # caller needs the assigned id before replicating
                    return results[0]
            except (StorageError, OSError) as e:
                node.record_failure()
                node.mark_stale()
                errors.append((node.label, e))
        if not results:
            raise StorageError(
                f"cluster metadata write failed everywhere: {errors!r}"
            )
        return results[0]


class ClusterApps(_ClusterMetaBase, base.Apps):
    DAO_CLS = _http.HTTPApps

    def insert(self, app):
        import dataclasses as _dc

        if app.id == 0:
            assigned = self._broadcast(
                lambda d: d.insert(app), primary_first=True
            )
            if assigned is None:
                return None
            app = _dc.replace(app, id=assigned)
            # replicate the EXPLICIT id to the rest (first node already
            # has it; re-insert there returns None harmlessly)
            self._broadcast(lambda d: d.insert(app))
            return assigned
        return self._broadcast(lambda d: d.insert(app))

    def get(self, app_id):
        return self._read(lambda d: d.get(app_id))

    def get_by_name(self, name):
        return self._read(lambda d: d.get_by_name(name))

    def get_all(self):
        return self._read(lambda d: d.get_all())

    def update(self, app):
        return self._broadcast(lambda d: d.update(app))

    def delete(self, app_id):
        return self._broadcast(lambda d: d.delete(app_id))


class ClusterAccessKeys(_ClusterMetaBase, base.AccessKeys):
    DAO_CLS = _http.HTTPAccessKeys

    def insert(self, access_key):
        import dataclasses as _dc

        if not access_key.key:
            # fix the key CLIENT-side so every replica stores the same
            access_key = _dc.replace(access_key, key=self.generate_key())
        out = self._broadcast(lambda d: d.insert(access_key))
        return out if out is not None else access_key.key

    def get(self, key):
        return self._read(lambda d: d.get(key))

    def get_all(self):
        return self._read(lambda d: d.get_all())

    def get_by_app_id(self, app_id):
        return self._read(lambda d: d.get_by_app_id(app_id))

    def update(self, access_key):
        return self._broadcast(lambda d: d.update(access_key))

    def delete(self, key):
        return self._broadcast(lambda d: d.delete(key))


class ClusterChannels(_ClusterMetaBase, base.Channels):
    DAO_CLS = _http.HTTPChannels

    def insert(self, channel):
        import dataclasses as _dc

        if channel.id == 0:
            assigned = self._broadcast(
                lambda d: d.insert(channel), primary_first=True
            )
            if assigned is None:
                return None
            channel = _dc.replace(channel, id=assigned)
            self._broadcast(lambda d: d.insert(channel))
            return assigned
        return self._broadcast(lambda d: d.insert(channel))

    def get(self, channel_id):
        return self._read(lambda d: d.get(channel_id))

    def get_by_app_id(self, app_id):
        return self._read(lambda d: d.get_by_app_id(app_id))

    def delete(self, channel_id):
        return self._broadcast(lambda d: d.delete(channel_id))


class ClusterEngineManifests(_ClusterMetaBase, base.EngineManifests):
    DAO_CLS = _http.HTTPEngineManifests

    def insert(self, manifest):
        return self._broadcast(lambda d: d.insert(manifest))

    def get(self, id, version):
        return self._read(lambda d: d.get(id, version))

    def get_all(self):
        return self._read(lambda d: d.get_all())

    def update(self, manifest, upsert=False):
        return self._broadcast(lambda d: d.update(manifest, upsert=upsert))

    def delete(self, id, version):
        return self._broadcast(lambda d: d.delete(id, version))


def _fixed_instance_id(instance):
    import dataclasses as _dc
    import uuid

    if instance.id:
        return instance
    return _dc.replace(instance, id=uuid.uuid4().hex[:17])


class ClusterEngineInstances(_ClusterMetaBase, base.EngineInstances):
    DAO_CLS = _http.HTTPEngineInstances

    def insert(self, instance):
        instance = _fixed_instance_id(instance)
        self._broadcast(lambda d: d.insert(instance))
        return instance.id

    def get(self, id):
        return self._read(lambda d: d.get(id))

    def get_all(self):
        return self._read(lambda d: d.get_all())

    def get_latest_completed(self, engine_id, engine_version, engine_variant):
        return self._read(
            lambda d: d.get_latest_completed(
                engine_id, engine_version, engine_variant
            )
        )

    def get_completed(self, engine_id, engine_version, engine_variant):
        return self._read(
            lambda d: d.get_completed(
                engine_id, engine_version, engine_variant
            )
        )

    def update(self, instance):
        return self._broadcast(lambda d: d.update(instance))

    def delete(self, id):
        return self._broadcast(lambda d: d.delete(id))


class ClusterEvaluationInstances(_ClusterMetaBase, base.EvaluationInstances):
    DAO_CLS = _http.HTTPEvaluationInstances

    def insert(self, instance):
        instance = _fixed_instance_id(instance)
        self._broadcast(lambda d: d.insert(instance))
        return instance.id

    def get(self, id):
        return self._read(lambda d: d.get(id))

    def get_all(self):
        return self._read(lambda d: d.get_all())

    def get_completed(self):
        return self._read(lambda d: d.get_completed())

    def update(self, instance):
        return self._broadcast(lambda d: d.update(instance))

    def delete(self, id):
        return self._broadcast(lambda d: d.delete(id))


class ClusterModels(_ClusterMetaBase, base.Models):
    DAO_CLS = _http.HTTPModels

    def insert(self, model):
        return self._broadcast(lambda d: d.insert(model))

    def get(self, id):
        return self._read(lambda d: d.get(id))

    def delete(self, id):
        return self._broadcast(lambda d: d.delete(id))
