"""Local-filesystem model blob store (reference localfs/LocalFSModels.scala:30-59).

Each model blob is one file ``<path>/pio_model_<id>``. The default MODELDATA
backend — model pytrees serialized by the workflow land here.
"""

from __future__ import annotations

import os

from predictionio_tpu.utils.fs import fs_basedir
import threading
from typing import Dict, Optional

from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import Model


class StorageClient(base.DAOCacheMixin):
    def __init__(self, config=None):
        self.config = config
        props = getattr(config, "properties", {}) or {}
        self.path = props.get("PATH") or os.path.join(
            fs_basedir(),
            "models",
        )
        os.makedirs(self.path, exist_ok=True)
        self._init_dao_cache()


class LocalFSModels(base.Models):
    def __init__(self, client: StorageClient, config=None, namespace: str = ""):
        self._path = client.path
        self._ns = namespace or "pio"

    def _file(self, id: str) -> str:
        safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in id)
        return os.path.join(self._path, f"{self._ns}_model_{safe}")

    def insert(self, model: Model) -> None:
        with open(self._file(model.id), "wb") as f:
            f.write(model.models)

    def get(self, id: str) -> Optional[Model]:
        try:
            with open(self._file(id), "rb") as f:
                return Model(id, f.read())
        except FileNotFoundError:
            return None

    def delete(self, id: str) -> None:
        try:
            os.remove(self._file(id))
        except FileNotFoundError:
            pass
