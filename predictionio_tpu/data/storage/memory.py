"""In-memory storage backend.

The embedded default for tests and local development — the role HBase/
Elasticsearch/LocalFS play in the reference, with the reference's DAO
semantics (per-app/channel event namespaces that must be ``init``-ed before
use, auto-increment ids, latest-completed queries). Thread-safe via a single
lock per DAO; adequate because all mutation paths are host-side metadata ops.
"""

from __future__ import annotations

import datetime as _dt
import threading
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from predictionio_tpu.data.event import Event, new_event_id
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import (
    UNSET,
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EngineManifest,
    EvaluationInstance,
    Model,
    OptFilter,
    StorageError,
)


class MemLEvents(base.LEvents):
    def __init__(self, client=None, config=None, namespace: str = ""):
        self._lock = threading.RLock()
        # {(app_id, channel_id): {event_id: Event}} with per-namespace
        # insertion-ordered dicts; find() sorts by event time on read.
        self._tables: Dict[Tuple[int, Optional[int]], Dict[str, Event]] = {}
        # monotone mutation counter: the store-fingerprint component that
        # distinguishes e.g. delete-then-reinsert from a no-op
        self._mutations = 0
        # monotone DESTRUCTIVE counter: bumps only when an already-stored
        # event is removed or overwritten (delete, explicit-id re-post).
        # Unchanged counter + grown table == strictly append-only since,
        # which is what lets the delta scan replay just the tail.
        self._destructive = 0

    def _table(self, app_id: int, channel_id: Optional[int]) -> Dict[str, Event]:
        key = (app_id, channel_id)
        if key not in self._tables:
            raise StorageError(
                f"events table for app {app_id} channel {channel_id} not "
                "initialized; run init() (pio app new) first"
            )
        return self._tables[key]

    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        with self._lock:
            self._tables.setdefault((app_id, channel_id), {})
        return True

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        with self._lock:
            found = self._tables.pop((app_id, channel_id), None) is not None
            if found:
                # dropping a table destroys every covered row: a delta
                # cursor taken before must never validate afterwards,
                # even if the table is re-init'ed and refilled
                self._mutations += 1
                self._destructive += 1
            return found

    def close(self) -> None:
        pass

    def insert(self, event: Event, app_id: int, channel_id: Optional[int] = None) -> str:
        with self._lock:
            table = self._table(app_id, channel_id)
            eid = event.event_id or new_event_id()
            if eid in table:
                self._destructive += 1  # explicit-id re-post: REPLACE
            table[eid] = event.with_event_id(eid)
            self._mutations += 1
            return eid

    def insert_batch(
        self,
        events,
        app_id: int,
        channel_id: Optional[int] = None,
    ) -> List[str]:
        """Atomic batch insert: the whole batch lands under ONE lock
        acquisition (readers copy under the same lock, so no reader can
        observe a partial batch) and bumps the mutation counter once —
        the same group-commit contract the sqlite committer provides
        (base.LEvents.insert_batch)."""
        with self._lock:
            table = self._table(app_id, channel_id)
            eids = []
            for event in events:
                eid = event.event_id or new_event_id()
                if eid in table:
                    self._destructive += 1  # explicit-id re-post
                table[eid] = event.with_event_id(eid)
                eids.append(eid)
            if eids:
                self._mutations += 1
            return eids

    def get(
        self, event_id: str, app_id: int, channel_id: Optional[int] = None
    ) -> Optional[Event]:
        with self._lock:
            return self._table(app_id, channel_id).get(event_id)

    def delete(
        self, event_id: str, app_id: int, channel_id: Optional[int] = None
    ) -> bool:
        with self._lock:
            found = self._table(app_id, channel_id).pop(event_id, None) is not None
            if found:
                self._mutations += 1
                self._destructive += 1
            return found

    def store_fingerprint(
        self, app_id: int, channel_id: Optional[int] = None
    ) -> Optional[tuple]:
        with self._lock:
            table = self._tables.get((app_id, channel_id))
            if table is None:
                return None
            return ("memory", len(table), self._mutations)

    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: OptFilter = UNSET,
        target_entity_id: OptFilter = UNSET,
        limit: Optional[int] = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        with self._lock:
            events = list(self._table(app_id, channel_id).values())
        names = set(event_names) if event_names is not None else None
        start_time = _aware(start_time)
        until_time = _aware(until_time)
        out = [
            e
            for e in events
            if _matches(
                e, start_time, until_time, entity_type, entity_id,
                names, target_entity_type, target_entity_id,
            )
        ]
        out.sort(key=lambda e: e.event_time, reverse=reversed)
        if limit is not None and limit >= 0:
            out = out[:limit]
        return iter(out)

    def stream_columns_native(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        value_spec=None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        target_entity_type: OptFilter = UNSET,
        event_names: Optional[Sequence[str]] = None,
        batch_rows: int = 1_048_576,
    ):
        """One-batch stream (in-memory scale needs no chunking) with the
        same wire the generic ``find_columns_native`` fallback produces,
        PLUS a delta cursor: the scan-time table length, the destructive
        counter, and the max matched event time — everything the
        append-only tail replay (``stream_columns_delta``) re-validates.
        Snapshot and counters are read under ONE lock acquisition, so
        the cursor can never be newer than the data it describes."""
        from predictionio_tpu.data.storage.columnar import (
            ColumnarStream,
            ValueSpec,
            from_events,
        )

        spec = value_spec or ValueSpec()
        with self._lock:
            n_table = len(self._table(app_id, channel_id))
            destructive = self._destructive
            fingerprint = ("memory", n_table, self._mutations)
            events = list(self._table(app_id, channel_id).values())
        kept = self._matching_targetful(
            events, start_time, until_time, entity_type,
            target_entity_type, event_names,
        )
        max_t = max((e.event_time for e in kept), default=None)
        cursor = (
            "memory-delta", app_id, channel_id, n_table, destructive,
            max_t,
        )
        return ColumnarStream.from_columnar(
            from_events(kept, spec),
            fingerprint=fingerprint,
            cursor_fn=lambda: cursor,
        )

    @staticmethod
    def _matching_targetful(
        events, start_time, until_time, entity_type, target_entity_type,
        event_names,
    ) -> List[Event]:
        """The columnar-scan selection: filter like ``find``, keep only
        target-carrying events, sort by event time (stable — insertion
        order breaks ties, which is what makes an appended tail agree
        with a full re-sort)."""
        names = set(event_names) if event_names is not None else None
        start_time = _aware(start_time)
        until_time = _aware(until_time)
        kept = [
            e
            for e in events
            if e.target_entity_id is not None
            and _matches(
                e, start_time, until_time, entity_type, None, names,
                target_entity_type, UNSET,
            )
        ]
        kept.sort(key=lambda e: e.event_time)
        return kept

    def stream_columns_delta(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        cursor: tuple,
        value_spec=None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        target_entity_type: OptFilter = UNSET,
        event_names: Optional[Sequence[str]] = None,
        batch_rows: int = 1_048_576,
    ):
        """Append-only tail replay: valid only while no event the prior
        scan covered was deleted or overwritten (destructive counter
        unchanged) AND every new matching event's time is >= the prior
        scan's max — the memory wire is EVENT-TIME ordered, so an
        out-of-order arrival would sort into the already-folded prefix
        and needs the full repack."""
        from predictionio_tpu.data.storage.columnar import (
            ColumnarStream,
            ValueSpec,
            from_events,
        )

        if (
            not isinstance(cursor, tuple)
            or len(cursor) != 6
            or cursor[0] != "memory-delta"
            or (cursor[1], cursor[2]) != (app_id, channel_id)
        ):
            return None
        _, _, _, n_then, destructive_then, max_t = cursor
        spec = value_spec or ValueSpec()
        with self._lock:
            table = self._tables.get((app_id, channel_id))
            if table is None or self._destructive != destructive_then:
                return None
            events = list(table.values())
            fingerprint = ("memory", len(events), self._mutations)
        if len(events) < n_then:
            return None
        kept = self._matching_targetful(
            events[n_then:], start_time, until_time, entity_type,
            target_entity_type, event_names,
        )
        if max_t is not None and any(e.event_time < max_t for e in kept):
            return None  # out-of-order arrival: sorts into the prefix
        new_max = max((e.event_time for e in kept), default=max_t)
        new_cursor = (
            "memory-delta", app_id, channel_id, len(events),
            destructive_then, new_max,
        )
        return ColumnarStream.from_columnar(
            from_events(kept, spec),
            fingerprint=fingerprint,
            cursor_fn=lambda: new_cursor,
        )


def _matches(
    e: Event, start_time, until_time, entity_type, entity_id, names,
    target_entity_type, target_entity_id,
) -> bool:
    """The ``find()`` filter predicate (time bounds already tz-aware,
    ``names`` already a set or None) — shared with the columnar scans so
    a delta tail is selected by EXACTLY the full scan's rules."""
    if start_time is not None and e.event_time < start_time:
        return False
    if until_time is not None and e.event_time >= until_time:
        return False
    if entity_type is not None and e.entity_type != entity_type:
        return False
    if entity_id is not None and e.entity_id != entity_id:
        return False
    if names is not None and e.event not in names:
        return False
    if target_entity_type is not UNSET and e.target_entity_type != target_entity_type:
        return False
    if target_entity_id is not UNSET and e.target_entity_id != target_entity_id:
        return False
    return True


def _utcnow():
    return _dt.datetime.now(_dt.timezone.utc)


def _aware(t: Optional[_dt.datetime]) -> Optional[_dt.datetime]:
    if t is not None and t.tzinfo is None:
        return t.replace(tzinfo=_dt.timezone.utc)
    return t


class MemApps(base.Apps):
    def __init__(self, client=None, config=None, namespace: str = ""):
        self._lock = threading.RLock()
        self._apps: Dict[int, App] = {}
        self._next_id = 1

    def insert(self, app: App) -> Optional[int]:
        with self._lock:
            if any(a.name == app.name for a in self._apps.values()):
                return None
            app_id = app.id
            if app_id == 0:
                app_id = self._next_id
            if app_id in self._apps:
                return None
            self._next_id = max(self._next_id, app_id + 1)
            self._apps[app_id] = App(app_id, app.name, app.description)
            return app_id

    def get(self, app_id: int) -> Optional[App]:
        return self._apps.get(app_id)

    def get_by_name(self, name: str) -> Optional[App]:
        with self._lock:
            for a in self._apps.values():
                if a.name == name:
                    return a
        return None

    def get_all(self) -> List[App]:
        with self._lock:
            return sorted(self._apps.values(), key=lambda a: a.id)

    def update(self, app: App) -> bool:
        with self._lock:
            if app.id not in self._apps:
                return False
            self._apps[app.id] = app
            return True

    def delete(self, app_id: int) -> bool:
        with self._lock:
            return self._apps.pop(app_id, None) is not None


class MemAccessKeys(base.AccessKeys):
    def __init__(self, client=None, config=None, namespace: str = ""):
        self._lock = threading.RLock()
        self._keys: Dict[str, AccessKey] = {}

    def insert(self, access_key: AccessKey) -> Optional[str]:
        with self._lock:
            key = access_key.key or self.generate_key()
            if key in self._keys:
                return None
            self._keys[key] = AccessKey(key, access_key.appid, access_key.events)
            return key

    def get(self, key: str) -> Optional[AccessKey]:
        return self._keys.get(key)

    def get_all(self) -> List[AccessKey]:
        with self._lock:
            return list(self._keys.values())

    def get_by_app_id(self, app_id: int) -> List[AccessKey]:
        with self._lock:
            return [k for k in self._keys.values() if k.appid == app_id]

    def update(self, access_key: AccessKey) -> bool:
        with self._lock:
            if access_key.key not in self._keys:
                return False
            self._keys[access_key.key] = access_key
            return True

    def delete(self, key: str) -> bool:
        with self._lock:
            return self._keys.pop(key, None) is not None


class MemChannels(base.Channels):
    def __init__(self, client=None, config=None, namespace: str = ""):
        self._lock = threading.RLock()
        self._channels: Dict[int, Channel] = {}
        self._next_id = 1

    def insert(self, channel: Channel) -> Optional[int]:
        if not Channel.is_valid_name(channel.name):
            return None
        with self._lock:
            cid = channel.id or self._next_id
            if cid in self._channels:
                return None
            self._next_id = max(self._next_id, cid + 1)
            self._channels[cid] = Channel(cid, channel.name, channel.appid)
            return cid

    def get(self, channel_id: int) -> Optional[Channel]:
        return self._channels.get(channel_id)

    def get_by_app_id(self, app_id: int) -> List[Channel]:
        with self._lock:
            return [c for c in self._channels.values() if c.appid == app_id]

    def delete(self, channel_id: int) -> bool:
        with self._lock:
            return self._channels.pop(channel_id, None) is not None


class MemEngineManifests(base.EngineManifests):
    def __init__(self, client=None, config=None, namespace: str = ""):
        self._lock = threading.RLock()
        self._manifests: Dict[Tuple[str, str], EngineManifest] = {}

    def insert(self, manifest: EngineManifest) -> None:
        with self._lock:
            self._manifests[(manifest.id, manifest.version)] = manifest

    def get(self, id: str, version: str) -> Optional[EngineManifest]:
        return self._manifests.get((id, version))

    def get_all(self) -> List[EngineManifest]:
        with self._lock:
            return list(self._manifests.values())

    def update(self, manifest: EngineManifest, upsert: bool = False) -> None:
        with self._lock:
            key = (manifest.id, manifest.version)
            if key in self._manifests or upsert:
                self._manifests[key] = manifest

    def delete(self, id: str, version: str) -> None:
        with self._lock:
            self._manifests.pop((id, version), None)


def _new_instance_id() -> str:
    import uuid

    return uuid.uuid4().hex[:17]


class MemEngineInstances(base.EngineInstances):
    def __init__(self, client=None, config=None, namespace: str = ""):
        self._lock = threading.RLock()
        self._instances: Dict[str, EngineInstance] = {}

    def insert(self, instance: EngineInstance) -> str:
        import dataclasses as _dc

        with self._lock:
            iid = instance.id or _new_instance_id()
            self._instances[iid] = _dc.replace(instance, id=iid)
            return iid

    def get(self, id: str) -> Optional[EngineInstance]:
        return self._instances.get(id)

    def get_all(self) -> List[EngineInstance]:
        with self._lock:
            return list(self._instances.values())

    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> List[EngineInstance]:
        with self._lock:
            out = [
                i
                for i in self._instances.values()
                if i.status == base.STATUS_COMPLETED
                and i.engine_id == engine_id
                and i.engine_version == engine_version
                and i.engine_variant == engine_variant
            ]
        out.sort(key=lambda i: i.start_time, reverse=True)
        return out

    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> Optional[EngineInstance]:
        completed = self.get_completed(engine_id, engine_version, engine_variant)
        return completed[0] if completed else None

    def update(self, instance: EngineInstance) -> None:
        with self._lock:
            self._instances[instance.id] = instance

    def delete(self, id: str) -> None:
        with self._lock:
            self._instances.pop(id, None)


class MemEvaluationInstances(base.EvaluationInstances):
    def __init__(self, client=None, config=None, namespace: str = ""):
        self._lock = threading.RLock()
        self._instances: Dict[str, EvaluationInstance] = {}

    def insert(self, instance: EvaluationInstance) -> str:
        import dataclasses as _dc

        with self._lock:
            iid = instance.id or _new_instance_id()
            self._instances[iid] = _dc.replace(instance, id=iid)
            return iid

    def get(self, id: str) -> Optional[EvaluationInstance]:
        return self._instances.get(id)

    def get_all(self) -> List[EvaluationInstance]:
        with self._lock:
            return list(self._instances.values())

    def get_completed(self) -> List[EvaluationInstance]:
        with self._lock:
            out = [
                i
                for i in self._instances.values()
                if i.status == base.STATUS_COMPLETED
            ]
        out.sort(key=lambda i: i.start_time, reverse=True)
        return out

    def update(self, instance: EvaluationInstance) -> None:
        with self._lock:
            self._instances[instance.id] = instance

    def delete(self, id: str) -> None:
        with self._lock:
            self._instances.pop(id, None)


class MemModels(base.Models):
    def __init__(self, client=None, config=None, namespace: str = ""):
        self._lock = threading.RLock()
        self._models: Dict[str, Model] = {}

    def insert(self, model: Model) -> None:
        with self._lock:
            self._models[model.id] = model

    def get(self, id: str) -> Optional[Model]:
        return self._models.get(id)

    def delete(self, id: str) -> None:
        with self._lock:
            self._models.pop(id, None)


class StorageClient(base.DAOCacheMixin):
    """Client object for the memory backend. Holds shared DAO instances so
    that every lookup of the same source returns the same data."""

    def __init__(self, config=None):
        self.config = config
        self._init_dao_cache()
