"""Storage DAO interfaces and metadata records.

Capability parity with the reference storage layer
(data/src/main/scala/io/prediction/data/storage/): the ``LEvents`` event DAO
trait (LEvents.scala:37-328), and the seven metadata DAOs — Apps
(Apps.scala:29-57), AccessKeys (AccessKeys.scala:31-64), Channels
(Channels.scala:29-78), EngineManifests (EngineManifests.scala:34-62),
EngineInstances (EngineInstances.scala:43-94), EvaluationInstances
(EvaluationInstances.scala:39-78), Models (Models.scala:30-48).

The reference splits event access into a local (LEvents) and a Spark-RDD
(PEvents) trait; in the single-controller TPU runtime one DAO serves both
roles — bulk reads return host iterators that the store layer columnarizes
into device-bound batches (see predictionio_tpu.data.store).
"""

from __future__ import annotations

import abc
import dataclasses
import datetime as _dt
import re
import secrets
import threading
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Union


class DAOCacheMixin:
    """Per-(DAO class, namespace) instance cache for backend StorageClients
    (the reference caches clients per source, Storage.scala:202-208). Call
    ``_init_dao_cache`` in __init__; pass a lock to share one with other
    client state (e.g. sqlite's connection lock)."""

    def _init_dao_cache(self, lock: Optional[threading.Lock] = None) -> None:
        self._daos: Dict[str, object] = {}
        self._dao_lock = lock if lock is not None else threading.Lock()

    def dao(self, cls, namespace: str):
        key = f"{cls.__name__}:{namespace}"
        with self._dao_lock:
            if key not in self._daos:
                self._daos[key] = cls(
                    client=self, config=self.config, namespace=namespace
                )
            return self._daos[key]


class _Unset:
    """Sentinel distinguishing 'filter not given' from 'filter for absent'."""

    _instance: Optional["_Unset"] = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "UNSET"


UNSET = _Unset()
OptFilter = Union[_Unset, None, str]

from predictionio_tpu.data.event import Event  # noqa: E402


class StorageError(Exception):
    """Backend failure (reference StorageException, Storage.scala:85-105)."""


class StorageSaturatedError(StorageError):
    """The write path is at capacity RIGHT NOW (a bounded group-commit
    queue refused a unit within its admission window). Distinct from a
    plain StorageError so frontends can answer deliberate backpressure
    (503 + ``Retry-After``) instead of parking a handler thread
    unboundedly behind a wedged or overloaded committer. ``retry_after_s``
    is the hint frontends surface to clients."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class PartialBatchError(StorageError):
    """An ``insert_batch`` where some per-partition slices committed and
    others failed. ``event_ids`` is the full assigned-id list (input
    order); ``failed_ids`` the subset whose slice did NOT commit — so a
    caller (the batch REST route) can report per-event outcomes instead
    of disavowing the whole batch after part of it is durable.
    ``retry_after_s``, when set, marks the failures as capacity refusals
    (the :class:`StorageSaturatedError` case scoped to a slice): the
    failed slots are retryable after backoff, and frontends answer them
    503 instead of 500."""

    def __init__(
        self,
        message: str,
        event_ids,
        failed_ids,
        retry_after_s: Optional[float] = None,
    ):
        super().__init__(message)
        self.event_ids = list(event_ids)
        self.failed_ids = frozenset(failed_ids)
        self.retry_after_s = (
            None if retry_after_s is None else float(retry_after_s)
        )


class LEvents(abc.ABC):
    """Event CRUD DAO (reference LEvents.scala:37-328).

    All operations are synchronous; the reference's Future-based API exists
    to paper over blocking JVM clients, which a Python host thread does not
    need. REST servers run these on worker threads.
    """

    @abc.abstractmethod
    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        """Initialize the backing table/namespace for an app (channel)."""

    @abc.abstractmethod
    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        """Remove all data for an app (channel)."""

    @abc.abstractmethod
    def close(self) -> None:
        """Release client connections."""

    @abc.abstractmethod
    def insert(self, event: Event, app_id: int, channel_id: Optional[int] = None) -> str:
        """Insert one event; returns the assigned eventId."""

    @abc.abstractmethod
    def get(
        self, event_id: str, app_id: int, channel_id: Optional[int] = None
    ) -> Optional[Event]:
        """Get one event by id."""

    @abc.abstractmethod
    def delete(
        self, event_id: str, app_id: int, channel_id: Optional[int] = None
    ) -> bool:
        """Delete one event by id; returns whether it existed."""

    @abc.abstractmethod
    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: OptFilter = UNSET,
        target_entity_id: OptFilter = UNSET,
        limit: Optional[int] = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        """Find events with the reference's 9 filter dimensions
        (LEvents.scala:164-176). ``start_time`` inclusive, ``until_time``
        exclusive. ``target_entity_type=None`` (explicitly) filters for
        events *without* a target entity; leave UNSET to not filter.
        ``limit=None`` or -1 returns all. ``reversed`` returns descending
        event-time order."""

    # --- derived operations ---

    def aggregate_properties(
        self,
        app_id: int,
        entity_type: str,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        required: Optional[Sequence[str]] = None,
    ) -> Dict[str, "PropertyMap"]:
        """Aggregate $set/$unset/$delete into per-entity PropertyMaps
        (reference LEvents.futureAggregateProperties:191-214)."""
        from predictionio_tpu.data.aggregator import aggregate_properties

        events = self.find(
            app_id=app_id,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            event_names=["$set", "$unset", "$delete"],
        )
        result = aggregate_properties(events)
        if required:
            req = list(required)
            result = {
                k: v for k, v in result.items() if all(r in v for r in req)
            }
        return result

    def aggregate_properties_of_entity(
        self,
        app_id: int,
        entity_type: str,
        entity_id: str,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
    ) -> Optional["PropertyMap"]:
        """Single-entity variant (reference LEvents.scala:234-253)."""
        from predictionio_tpu.data.aggregator import aggregate_properties_single

        events = self.find(
            app_id=app_id,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=["$set", "$unset", "$delete"],
        )
        return aggregate_properties_single(events)

    def insert_batch(
        self,
        events: Sequence[Event],
        app_id: int,
        channel_id: Optional[int] = None,
    ) -> List[str]:
        """Insert a group of events as ONE batch, returning their ids in
        input order. This is the group-commit unit of the event tier:
        the ``/batch/events.json`` route hands its whole request here,
        so a backend can make it one transaction instead of N.

        Contract for backends that override it: the batch must be
        atomic per storage partition — a reader may never observe part
        of a partition's slice (sqlite commits each shard's slice as one
        transaction; memory applies the whole batch under one lock
        acquisition). This generic fallback loops ``insert`` and is NOT
        atomic — acceptable for backends with per-event durability only.
        """
        return [self.insert(e, app_id, channel_id) for e in events]

    def write(
        self, events: Iterable[Event], app_id: int, channel_id: Optional[int] = None
    ) -> List[str]:
        """Bulk insert (reference PEvents.write:169-181) — rides the
        batch path so backends with a group-commit writer coalesce it."""
        return self.insert_batch(list(events), app_id, channel_id)

    # --- columnar scan path (round 4; reference analog: the partitioned
    # columnar scans HBPEvents.scala:84-90 / JDBCPEvents.scala:51-129) ---

    def insert_columns(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        event: str,
        entity_type: str,
        target_entity_type: str,
        entity_ids: Sequence[str],
        target_ids: Sequence[str],
        values: Sequence[float],
        value_property: str = "rating",
        event_time: Optional[_dt.datetime] = None,
        event_times_ms: Optional[Sequence[int]] = None,
    ) -> int:
        """Bulk-append target-carrying interaction events from columns.

        Backends with a columnar page store (sqlite) override this with a
        vectorized dictionary-encoded append; this generic fallback
        constructs one Event per row. ``event`` must be a plain
        interaction event (not a ``$``-prefixed special event — those
        carry property semantics the columnar form does not model).
        ``event_times_ms`` gives per-row millisecond timestamps (import
        round-trips); otherwise every row gets ``event_time`` (default
        now). Returns the number of events written.
        """
        if event.startswith("$"):
            raise StorageError(
                f"insert_columns cannot write special event {event!r}"
            )
        if event_times_ms is not None and len(event_times_ms) != len(values):
            # validate BEFORE the lazy generator: a short array failing
            # mid-write would leave a partial import behind
            raise ValueError("event_times_ms length differs")
        from predictionio_tpu.data.event import DataMap, Event

        t = event_time or _dt.datetime.now(_dt.timezone.utc)

        def when(j: int) -> _dt.datetime:
            if event_times_ms is None:
                return t
            return _dt.datetime.fromtimestamp(
                event_times_ms[j] / 1000.0, _dt.timezone.utc
            )

        self.write(
            (
                Event(
                    event=event,
                    entity_type=entity_type,
                    entity_id=str(e),
                    target_entity_type=target_entity_type,
                    target_entity_id=str(g),
                    properties=DataMap({value_property: float(v)}),
                    event_time=when(j),
                )
                for j, (e, g, v) in enumerate(
                    zip(entity_ids, target_ids, values)
                )
            ),
            app_id,
            channel_id,
        )
        return len(values)

    def insert_columns_encoded(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        event: str,
        entity_type: str,
        target_entity_type: str,
        entity_names,
        entity_codes,
        target_names,
        target_codes,
        values,
        value_property: str = "rating",
        event_time: Optional[_dt.datetime] = None,
        event_times_ms=None,
    ) -> int:
        """``insert_columns`` with pre-factorized id columns (distinct
        name dictionaries + int32 codes) — what travels over the storage
        gateway wire. Backends with a dictionary-encoded page store
        (sqlite) consume this directly; this generic fallback expands the
        codes back to id strings."""
        import numpy as np

        e_names = np.asarray(entity_names, object)
        g_names = np.asarray(target_names, object)
        return self.insert_columns(
            app_id,
            channel_id,
            event=event,
            entity_type=entity_type,
            target_entity_type=target_entity_type,
            entity_ids=e_names[np.asarray(entity_codes, np.int64)],
            target_ids=g_names[np.asarray(target_codes, np.int64)],
            values=values,
            value_property=value_property,
            event_time=event_time,
            event_times_ms=event_times_ms,
        )

    def find_columns_native(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        value_spec=None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        target_entity_type: OptFilter = UNSET,
        event_names: Optional[Sequence[str]] = None,
    ):
        """Columnar scan: dictionary-encoded (entity, target, value)
        triples of every target-carrying event matching the filters
        (``ColumnarEvents``). ``value_spec`` (a ``columnar.ValueSpec``)
        declares how an event becomes a value, so backends can evaluate
        it vectorized (SQL / page decode) instead of per event.

        This generic implementation columnarizes ``find()`` results
        host-side; the sqlite backend overrides it with a binary page
        scan and the http backend forwards it to the gateway so the wire
        carries packed columns, not per-event JSON.
        """
        from predictionio_tpu.data.storage.columnar import (
            ValueSpec,
            from_events,
        )

        events = list(
            self.find(
                app_id=app_id,
                channel_id=channel_id,
                start_time=start_time,
                until_time=until_time,
                entity_type=entity_type,
                target_entity_type=target_entity_type,
                event_names=event_names,
            )
        )
        return from_events(events, value_spec or ValueSpec())

    def stream_columns_native(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        value_spec=None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        target_entity_type: OptFilter = UNSET,
        event_names: Optional[Sequence[str]] = None,
        batch_rows: int = 1_048_576,
    ):
        """Chunked columnar scan (``columnar.ColumnarStream``): fixed-size
        batches in one shared code space, so the training pipeline can
        pack batch k while the backend scans batch k+1.

        Returns None when the backend has no chunked path — callers fall
        back to ``find_columns_native`` (one batch, no overlap). The
        sqlite backend overrides this with a per-page binary scan.
        """
        return None

    def stream_columns_delta(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        cursor: tuple,
        value_spec=None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        target_entity_type: OptFilter = UNSET,
        event_names: Optional[Sequence[str]] = None,
        batch_rows: int = 1_048_576,
    ):
        """Incremental columnar scan: ONLY the target-carrying events
        committed after ``cursor`` (an opaque value a previous
        ``stream_columns_native``/``stream_columns_delta`` of the SAME
        app/channel/filters exposed via ``ColumnarStream.cursor``), in
        the order a full rescan would emit them after the rows the
        cursor already covered. The returned stream's own ``cursor``
        (valid after exhaustion) chains the next round.

        Contract — a backend may only return a stream when appending the
        delta to the prior scan reproduces a full rescan of the CURRENT
        store exactly; anything that rewrote or reordered already-scanned
        rows (deletes, tombstones, explicit-id re-posts, bulk-import page
        changes, a changed shard layout) must return ``None`` instead, so
        the caller falls back to a full repack. This default has no delta
        path at all; sqlite scans above per-shard rowid high-water marks
        (compaction watermarks guarantee sealed prefixes never re-issue
        rowids), memory replays its append-only tail.
        """
        return None

    def store_fingerprint(
        self, app_id: int, channel_id: Optional[int] = None
    ) -> Optional[tuple]:
        """Cheap state fingerprint of one app/channel's event store —
        event counts, max ids/times, tombstone state — used to key the
        pack-artifact cache: a repeat train whose fingerprint matches the
        cached one skips scan+pack entirely. Must change whenever a scan
        of the store could return different columns (insert, bulk import,
        delete). None disables caching for this backend.
        """
        return None


# --- metadata records ---


@dataclasses.dataclass(frozen=True)
class App:
    """An app record (reference Apps.scala:29)."""

    id: int
    name: str
    description: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class AccessKey:
    """An access key granting event-API access to an app
    (reference AccessKeys.scala:31). Empty ``events`` permits all."""

    key: str
    appid: int
    events: tuple = ()

    def __post_init__(self):
        object.__setattr__(self, "events", tuple(self.events))


@dataclasses.dataclass(frozen=True)
class Channel:
    """A named event channel within an app (reference Channels.scala:29)."""

    id: int
    name: str
    appid: int

    NAME_RE = re.compile(r"^[a-zA-Z0-9-]{1,16}$")

    @staticmethod
    def is_valid_name(s: str) -> bool:
        return bool(Channel.NAME_RE.match(s))


@dataclasses.dataclass(frozen=True)
class EngineManifest:
    """A built engine's registration (reference EngineManifests.scala:34)."""

    id: str
    version: str
    name: str
    description: Optional[str] = None
    files: tuple = ()
    engine_factory: str = ""

    def __post_init__(self):
        object.__setattr__(self, "files", tuple(self.files))


@dataclasses.dataclass(frozen=True)
class EngineInstance:
    """A training-run record (reference EngineInstances.scala:43-94)."""

    id: str
    status: str
    start_time: _dt.datetime
    end_time: _dt.datetime
    engine_id: str
    engine_version: str
    engine_variant: str
    engine_factory: str
    batch: str = ""
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    spark_conf: Dict[str, str] = dataclasses.field(default_factory=dict)
    data_source_params: str = ""
    preparator_params: str = ""
    algorithms_params: str = ""
    serving_params: str = ""


@dataclasses.dataclass(frozen=True)
class EvaluationInstance:
    """An evaluation-run record (reference EvaluationInstances.scala:39-78)."""

    id: str
    status: str
    start_time: _dt.datetime
    end_time: _dt.datetime
    evaluation_class: str = ""
    engine_params_generator_class: str = ""
    batch: str = ""
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    spark_conf: Dict[str, str] = dataclasses.field(default_factory=dict)
    evaluator_results: str = ""
    evaluator_results_html: str = ""
    evaluator_results_json: str = ""


@dataclasses.dataclass(frozen=True)
class Model:
    """A serialized model blob keyed by engine-instance id
    (reference Models.scala:30)."""

    id: str
    models: bytes


# --- metadata DAO interfaces ---


class Apps(abc.ABC):
    @abc.abstractmethod
    def insert(self, app: App) -> Optional[int]:
        """Insert; id 0 means auto-assign. Returns the assigned id."""

    @abc.abstractmethod
    def get(self, app_id: int) -> Optional[App]: ...

    @abc.abstractmethod
    def get_by_name(self, name: str) -> Optional[App]: ...

    @abc.abstractmethod
    def get_all(self) -> List[App]: ...

    @abc.abstractmethod
    def update(self, app: App) -> bool: ...

    @abc.abstractmethod
    def delete(self, app_id: int) -> bool: ...


class AccessKeys(abc.ABC):
    @abc.abstractmethod
    def insert(self, access_key: AccessKey) -> Optional[str]:
        """Insert; empty key means generate. Returns the key."""

    @abc.abstractmethod
    def get(self, key: str) -> Optional[AccessKey]: ...

    @abc.abstractmethod
    def get_all(self) -> List[AccessKey]: ...

    @abc.abstractmethod
    def get_by_app_id(self, app_id: int) -> List[AccessKey]: ...

    @abc.abstractmethod
    def update(self, access_key: AccessKey) -> bool: ...

    @abc.abstractmethod
    def delete(self, key: str) -> bool: ...

    @staticmethod
    def generate_key() -> str:
        """64-char URL-safe random key (reference AccessKeys.scala:44-49)."""
        while True:
            k = secrets.token_urlsafe(48).replace("-", "8").replace("_", "9")
            if len(k) >= 64:
                return k[:64]


class Channels(abc.ABC):
    @abc.abstractmethod
    def insert(self, channel: Channel) -> Optional[int]:
        """Insert; id 0 means auto-assign. Returns the assigned id."""

    @abc.abstractmethod
    def get(self, channel_id: int) -> Optional[Channel]: ...

    @abc.abstractmethod
    def get_by_app_id(self, app_id: int) -> List[Channel]: ...

    @abc.abstractmethod
    def delete(self, channel_id: int) -> bool: ...


class EngineManifests(abc.ABC):
    @abc.abstractmethod
    def insert(self, manifest: EngineManifest) -> None: ...

    @abc.abstractmethod
    def get(self, id: str, version: str) -> Optional[EngineManifest]: ...

    @abc.abstractmethod
    def get_all(self) -> List[EngineManifest]: ...

    @abc.abstractmethod
    def update(self, manifest: EngineManifest, upsert: bool = False) -> None: ...

    @abc.abstractmethod
    def delete(self, id: str, version: str) -> None: ...


class EngineInstances(abc.ABC):
    @abc.abstractmethod
    def insert(self, instance: EngineInstance) -> str:
        """Insert; empty id means generate. Returns the id."""

    @abc.abstractmethod
    def get(self, id: str) -> Optional[EngineInstance]: ...

    @abc.abstractmethod
    def get_all(self) -> List[EngineInstance]: ...

    @abc.abstractmethod
    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> Optional[EngineInstance]:
        """Latest COMPLETED instance for an engine variant
        (reference EngineInstances.getLatestCompleted:79)."""

    @abc.abstractmethod
    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> List[EngineInstance]: ...

    @abc.abstractmethod
    def update(self, instance: EngineInstance) -> None: ...

    @abc.abstractmethod
    def delete(self, id: str) -> None: ...


class EvaluationInstances(abc.ABC):
    @abc.abstractmethod
    def insert(self, instance: EvaluationInstance) -> str: ...

    @abc.abstractmethod
    def get(self, id: str) -> Optional[EvaluationInstance]: ...

    @abc.abstractmethod
    def get_all(self) -> List[EvaluationInstance]: ...

    @abc.abstractmethod
    def get_completed(self) -> List[EvaluationInstance]: ...

    @abc.abstractmethod
    def update(self, instance: EvaluationInstance) -> None: ...

    @abc.abstractmethod
    def delete(self, id: str) -> None: ...


class Models(abc.ABC):
    @abc.abstractmethod
    def insert(self, model: Model) -> None: ...

    @abc.abstractmethod
    def get(self, id: str) -> Optional[Model]: ...

    @abc.abstractmethod
    def delete(self, id: str) -> None: ...


# re-exported for type hints in aggregate_properties
from predictionio_tpu.data.event import PropertyMap  # noqa: E402

STATUS_INIT = "INIT"
STATUS_TRAINING = "TRAINING"
STATUS_EVALUATING = "EVALUATING"
STATUS_COMPLETED = "COMPLETED"
STATUS_FAILED = "FAILED"
