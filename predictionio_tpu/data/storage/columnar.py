"""Columnar event batches — the TPU-native answer to the reference's
partitioned event scans.

The reference's production event store is scanned in parallel, columnar
form: HBase region scans feeding RDD partitions
(data/src/main/scala/io/prediction/data/storage/hbase/HBPEvents.scala:84-90)
and the day-partitioned JDBC scan (jdbc/JDBCPEvents.scala:51-129). The
training path never materializes one JVM object per event — the scan IS
the columnar substrate.

Here the same role is played by **event pages**: bulk-imported events are
stored as dictionary-encoded numpy arrays (int32 entity/target codes, a
small string dictionary, float32 values, int64 ms timestamps) packed into
binary pages. A 20M-event scan is a handful of ``np.frombuffer`` calls
plus vectorized code remapping — no per-event Python objects, no JSON
parsing — and feeds ``jax.device_put`` directly. Per-event REST inserts
keep landing in the row store; scans merge pages with that residual tail,
so the two write paths stay transparently consistent.

``ValueSpec`` declares how an event becomes a training value (the
property to read, its default, and per-event-name constant overrides,
e.g. the recommendation template's ``buy -> 4.0``), so backends can
evaluate it vectorized instead of calling back into Python per event.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class ValueSpec:
    """Declarative per-event training value: ``event_overrides`` wins,
    else the numeric ``prop`` property, else ``default``."""

    prop: str = "rating"
    default: float = 1.0
    event_overrides: Tuple[Tuple[str, float], ...] = ()

    @property
    def overrides(self) -> Dict[str, float]:
        return dict(self.event_overrides)

    def value_of(self, event) -> float:
        """Per-event fallback (generic scan path)."""
        ov = self.overrides.get(event.event)
        if ov is not None:
            return float(ov)
        return float(event.properties.get_or_else(self.prop, self.default))


@dataclasses.dataclass
class ColumnarEvents:
    """Dictionary-encoded (entity, target, value) triples.

    ``entity_names[entity_codes[j]]`` is the j-th event's entity id. The
    name arrays are deduplicated and the codes dense (0..len(names)-1).
    """

    entity_names: np.ndarray  # [n_entities] str (object dtype)
    target_names: np.ndarray  # [n_targets] str
    entity_codes: np.ndarray  # [n] int32
    target_codes: np.ndarray  # [n] int32
    values: np.ndarray  # [n] float32

    @property
    def n(self) -> int:
        return len(self.values)

    @staticmethod
    def empty() -> "ColumnarEvents":
        return ColumnarEvents(
            entity_names=np.empty(0, object),
            target_names=np.empty(0, object),
            entity_codes=np.empty(0, np.int32),
            target_codes=np.empty(0, np.int32),
            values=np.empty(0, np.float32),
        )

    @staticmethod
    def concat(parts: Sequence["ColumnarEvents"]) -> "ColumnarEvents":
        """Merge batches, re-encoding codes against a deduplicated name
        dictionary (vectorized; names are catalog-sized, not event-sized)."""
        parts = [p for p in parts if p.n or len(p.entity_names)]
        if not parts:
            return ColumnarEvents.empty()
        if len(parts) == 1:
            return parts[0]

        def merge(names_list, codes_list):
            all_names = np.concatenate(
                [np.asarray(n, object) for n in names_list]
            )
            uniq, inverse = np.unique(all_names, return_inverse=True)
            out_codes = []
            offset = 0
            for names, codes in zip(names_list, codes_list):
                lut = inverse[offset : offset + len(names)].astype(np.int32)
                out_codes.append(lut[codes])
                offset += len(names)
            return uniq, np.concatenate(out_codes)

        e_names, e_codes = merge(
            [p.entity_names for p in parts], [p.entity_codes for p in parts]
        )
        t_names, t_codes = merge(
            [p.target_names for p in parts], [p.target_codes for p in parts]
        )
        return ColumnarEvents(
            entity_names=e_names,
            target_names=t_names,
            entity_codes=e_codes,
            target_codes=t_codes,
            values=np.concatenate([p.values for p in parts]).astype(
                np.float32
            ),
        )


class ColumnarStream:
    """Chunked columnar scan: an iterator of ``(entity_codes,
    target_codes, values)`` batches that all share ONE string-code space,
    plus the id-indexed ``names`` array resolving codes to id strings.

    This is the store→device streaming substrate (the role ALX's
    pre-bucketed input pipeline plays for TPU matrix factorization,
    PAPERS.md — arXiv:2112.02194): the training pipeline folds each batch
    into its pack structures while the backend is still scanning the
    next one, instead of materializing the whole event history first.

    Contract:
    - the code space may GROW while iterating (e.g. sqlite's row-store
      residual tail introduces ids absent from the page dictionary), so
      consumers size code-indexed accumulators from the codes they see
      and read ``names`` only after exhausting the iterator;
    - ``fingerprint`` is the producing store's cheap state fingerprint
      taken BEFORE the scan started (None when the backend can't provide
      one). Reading it pre-scan means a cached artifact can only ever be
      labeled with a fingerprint at least as old as its data — a
      concurrent write during the scan makes the next lookup miss, never
      hit stale;
    - ``cache_key``/``cache_scope`` identify the (app, channel, filters)
      and the producing DAO for the pack-artifact cache (the scope is
      compared by IDENTITY, never by a reusable ``id()``);
    - ``cursor`` (valid once the iterator is exhausted, like ``names``)
      is the backend's opaque delta cursor: the high-water state this
      scan actually covered. Feeding it back through ``delta_factory``
      (set by ``PEventStore.stream_columns``) yields a stream of ONLY
      the rows committed after it — the substrate of delta training
      (``ops/streaming``). ``None`` means the backend has no delta path
      and retrains rescan in full.
    """

    def __init__(
        self,
        batches,
        names_fn,
        fingerprint=None,
        cache_key=None,
        cache_scope=None,
        cursor_fn=None,
    ):
        self._batches = batches
        self._names_fn = names_fn
        self._cursor_fn = cursor_fn
        self.fingerprint = fingerprint
        self.cache_key = cache_key
        self.cache_scope = cache_scope
        # (cursor) -> Optional[ColumnarStream]: a delta scan of the same
        # app/filters from a prior scan's cursor (None: no delta path)
        self.delta_factory = None

    def __iter__(self):
        return iter(self._batches)

    @property
    def names(self) -> np.ndarray:
        """Id-indexed name array; valid once the iterator is exhausted."""
        return self._names_fn()

    @property
    def cursor(self):
        """Delta cursor covering exactly the rows this scan emitted;
        valid once the iterator is exhausted. None: no delta support."""
        return self._cursor_fn() if self._cursor_fn is not None else None

    @staticmethod
    def from_columnar(cols: ColumnarEvents, **kw) -> "ColumnarStream":
        """One-shot stream over a materialized scan (the generic
        fallback): entity codes keep their range, target codes shift past
        them, so the two sides share one code space."""
        e_names = np.asarray(cols.entity_names, object)
        t_names = np.asarray(cols.target_names, object)
        names = np.concatenate([e_names, t_names])
        ne = len(e_names)
        batches = (
            [(cols.entity_codes, cols.target_codes + np.int32(ne), cols.values)]
            if cols.n
            else []
        )
        return ColumnarStream(iter(batches), lambda: names, **kw)


def encode_strings(ids: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
    """Factorize string ids: (names [distinct, sorted], codes int32).

    Tiered for bulk-import scale (20M ids):
    - ASCII ids up to 8 chars pack into NATIVE uint64 words with the
      first char in the most significant byte, so integer order equals
      lexicographic order and np.unique runs an integer sort —
      measured 2.7x faster than the fixed-width-string sort at 20M
      (and ~10x faster than the big-endian ">u8" view, whose
      non-native compares fall back to a slow path).
    - other fixed-width numpy string arrays use their native dtype
      (C-speed memcmp sort; object arrays would compare Python strings
      one pair at a time, ~20x slower).
    Sorted-name order is identical across tiers (ASCII code points ==
    byte order), which PEventStore relies on for BiMap parity."""
    arr = np.asarray(ids)
    if arr.dtype.kind not in ("U", "S"):
        arr = np.asarray([str(x) for x in ids], dtype="U")
    packed = None
    if arr.dtype.kind == "U":
        try:
            packed = arr.astype("S")  # raises on non-ASCII -> slow tier
        except UnicodeEncodeError:
            packed = None
    else:
        packed = arr
    if packed is not None and 0 < packed.dtype.itemsize <= 8:
        k = packed.dtype.itemsize
        raw = np.zeros((len(packed), 8), np.uint8)
        raw[:, ::-1][:, :k] = packed.view(np.uint8).reshape(len(packed), k)
        words = raw.reshape(-1).view(np.uint64)
        names_w, codes = np.unique(words, return_inverse=True)
        name_bytes = (
            names_w.view(np.uint8).reshape(-1, 8)[:, ::-1][:, :k].tobytes()
        )
        names = np.frombuffer(name_bytes, dtype=f"S{k}")
        if arr.dtype.kind == "U":
            names = names.astype(f"U{k}")
        return names, codes.astype(np.int32)
    names, codes = np.unique(arr, return_inverse=True)
    return names, codes.astype(np.int32)


def array_to_b64(arr: np.ndarray) -> str:
    """Packed little-endian bytes, base64 — how numeric columns cross the
    storage-gateway JSON wire (33% overhead vs raw, no per-element JSON)."""
    import base64

    return base64.b64encode(np.ascontiguousarray(arr).tobytes()).decode()


def array_from_b64(s: str, dtype) -> np.ndarray:
    import base64

    return np.frombuffer(base64.b64decode(s), dtype=dtype)


def spec_to_wire(spec: ValueSpec) -> Dict:
    return {
        "prop": spec.prop,
        "default": spec.default,
        "overrides": [[k, v] for k, v in spec.event_overrides],
    }


def spec_from_wire(w: Optional[Dict]) -> ValueSpec:
    if not w:
        return ValueSpec()
    return ValueSpec(
        prop=w.get("prop", "rating"),
        default=float(w.get("default", 1.0)),
        event_overrides=tuple(
            (str(k), float(v)) for k, v in (w.get("overrides") or [])
        ),
    )


def columnar_to_wire(cols: ColumnarEvents) -> Dict:
    return {
        "entity_names": [str(n) for n in cols.entity_names],
        "target_names": [str(n) for n in cols.target_names],
        "entity_codes": array_to_b64(cols.entity_codes),
        "target_codes": array_to_b64(cols.target_codes),
        "values": array_to_b64(cols.values),
    }


def columnar_from_wire(w: Dict) -> ColumnarEvents:
    e_names = np.empty(len(w["entity_names"]), object)
    e_names[:] = w["entity_names"]
    t_names = np.empty(len(w["target_names"]), object)
    t_names[:] = w["target_names"]
    return ColumnarEvents(
        entity_names=e_names,
        target_names=t_names,
        entity_codes=array_from_b64(w["entity_codes"], np.int32),
        target_codes=array_from_b64(w["target_codes"], np.int32),
        values=array_from_b64(w["values"], np.float32),
    )


def from_events(events: List, spec: ValueSpec) -> ColumnarEvents:
    """Columnarize in-memory Event objects (the generic fallback and the
    memory backend's path — per-event Python, fine at in-memory scale)."""
    kept = [e for e in events if e.target_entity_id is not None]
    if not kept:
        return ColumnarEvents.empty()
    e_names, e_codes = encode_strings([e.entity_id for e in kept])
    t_names, t_codes = encode_strings([e.target_entity_id for e in kept])
    values = np.fromiter(
        (spec.value_of(e) for e in kept), np.float32, count=len(kept)
    )
    return ColumnarEvents(e_names, t_names, e_codes, t_codes, values)
