"""Pluggable storage registry (reference Storage.scala:112-393).

Backends are selected by configuration, not code: the environment (or an
explicit config dict) declares *sources* (named client configs with a TYPE)
and assigns the three *repositories* — METADATA, EVENTDATA, MODELDATA — to
sources, exactly mirroring the reference's
``PIO_STORAGE_SOURCES_<NAME>_TYPE/...`` and
``PIO_STORAGE_REPOSITORIES_{METADATA,EVENTDATA,MODELDATA}_{NAME,SOURCE}``
scheme (Storage.scala:122-191). DAO classes are resolved reflectively from
the backend module by naming convention ``<Prefix><DAOName>``
(Storage.scala:263-312), clients are cached per source (:202-208), and
``verify_all_data_objects`` provides the smoke probe (:325-348).

Built-in backends: ``memory`` (tests/dev), ``sqlite`` (persistent embedded
default), ``localfs`` (model blobs).

Backend-specific source keys ride the same scheme — notably the sqlite
write-path scale-out knobs (see data/storage/sqlite.py)::

    PIO_STORAGE_SOURCES_SQLITE_SHARDS=4            # event-row hash shards
    PIO_STORAGE_SOURCES_SQLITE_GROUP_COMMIT_EVENTS=512
    PIO_STORAGE_SOURCES_SQLITE_GROUP_COMMIT_MS=2
"""

from __future__ import annotations

import importlib
import os
import re
import threading
from typing import Dict, Optional

from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import (  # noqa: F401
    UNSET,
    AccessKey,
    AccessKeys,
    App,
    Apps,
    Channel,
    Channels,
    EngineInstance,
    EngineInstances,
    EngineManifest,
    EngineManifests,
    EvaluationInstance,
    EvaluationInstances,
    LEvents,
    Model,
    Models,
    StorageError,
)

# backend type -> (module path, DAO class prefix)
BUILTIN_BACKENDS: Dict[str, tuple] = {
    "memory": ("predictionio_tpu.data.storage.memory", "Mem"),
    "sqlite": ("predictionio_tpu.data.storage.sqlite", "SQLite"),
    "localfs": ("predictionio_tpu.data.storage.localfs", "LocalFS"),
    # client-server backend: DAOs proxied to a storage gateway service
    # (api/storage_gateway.py) — the HBase/JDBC/Elasticsearch role
    "http": ("predictionio_tpu.data.storage.http", "HTTP"),
    # partitioned, replicated gateway TIER: entity-hash routing over N
    # gateway nodes with R-way writes and failover scatter-gather scans
    # (data/storage/cluster.py) — the HBase-cluster role
    "cluster": ("predictionio_tpu.data.storage.cluster", "Cluster"),
}

REPOSITORIES = ("METADATA", "EVENTDATA", "MODELDATA")

_DEFAULT_ENV = {
    "PIO_STORAGE_SOURCES_SQLITE_TYPE": "sqlite",
    "PIO_STORAGE_SOURCES_LOCALFS_TYPE": "localfs",
    "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "pio_meta",
    "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQLITE",
    "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "pio_event",
    "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQLITE",
    "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "pio_model",
    "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "LOCALFS",
}

# all-memory config, used by tests and ephemeral servers
MEMORY_CONFIG = {
    "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
    "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "pio_meta",
    "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
    "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "pio_event",
    "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
    "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "pio_model",
    "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
}


class StorageClientConfig:
    """Per-source client config (reference StorageClientConfig,
    Storage.scala:73-76). ``properties`` holds the remaining
    ``PIO_STORAGE_SOURCES_<NAME>_<KEY>`` pairs keyed by KEY."""

    def __init__(self, properties: Optional[Dict[str, str]] = None):
        self.properties = dict(properties or {})

    def __repr__(self) -> str:
        return f"StorageClientConfig({self.properties!r})"


_SOURCE_RE = re.compile(r"^PIO_STORAGE_SOURCES_([^_]+)_(.+)$")
_REPO_RE = re.compile(r"^PIO_STORAGE_REPOSITORIES_([^_]+)_(NAME|SOURCE)$")


class Storage:
    """A configured storage universe: sources + repository assignments.

    Construct with an explicit config mapping, or without one to read the
    process environment (falling back to the sqlite/localfs defaults when no
    PIO_STORAGE_* variables are present).
    """

    def __init__(self, config: Optional[Dict[str, str]] = None):
        if config is None:
            env = {
                k: v for k, v in os.environ.items() if k.startswith("PIO_STORAGE_")
            }
            config = env if env else dict(_DEFAULT_ENV)
        self._config = dict(config)
        self._lock = threading.RLock()
        self._clients: Dict[str, object] = {}
        self._sources: Dict[str, Dict[str, str]] = {}
        self._repos: Dict[str, Dict[str, str]] = {}
        for k, v in self._config.items():
            m = _SOURCE_RE.match(k)
            if m:
                self._sources.setdefault(m.group(1), {})[m.group(2)] = v
                continue
            m = _REPO_RE.match(k)
            if m:
                self._repos.setdefault(m.group(1), {})[m.group(2)] = v
        for repo in REPOSITORIES:
            if repo not in self._repos or "SOURCE" not in self._repos[repo]:
                raise StorageError(
                    f"repository {repo} is not assigned a source; set "
                    f"PIO_STORAGE_REPOSITORIES_{repo}_SOURCE"
                )

    # --- source/client resolution ---

    def _source_conf(self, source_name: str) -> Dict[str, str]:
        conf = self._sources.get(source_name)
        if conf is None or "TYPE" not in conf:
            raise StorageError(
                f"storage source {source_name} is not configured; set "
                f"PIO_STORAGE_SOURCES_{source_name}_TYPE"
            )
        return conf

    def _client(self, source_name: str):
        with self._lock:
            if source_name not in self._clients:
                conf = self._source_conf(source_name)
                module, _ = self._backend(conf["TYPE"])
                props = {k: v for k, v in conf.items() if k != "TYPE"}
                self._clients[source_name] = module.StorageClient(
                    StorageClientConfig(props)
                )
            return self._clients[source_name]

    @staticmethod
    def _backend(type_name: str):
        if type_name in BUILTIN_BACKENDS:
            module_path, prefix = BUILTIN_BACKENDS[type_name]
        else:
            # extension point: a type names a module exposing PREFIX +
            # StorageClient + <PREFIX><DAOName> classes
            module_path, prefix = type_name, None
        try:
            module = importlib.import_module(module_path)
        except ImportError as e:
            raise StorageError(f"unknown storage backend type {type_name!r}") from e
        if prefix is None:
            prefix = getattr(module, "PREFIX", "")
        return module, prefix

    def get_data_object(self, source_name: str, namespace: str, dao_name: str):
        """Reflective DAO lookup (reference Storage.getDataObject:263-312)."""
        conf = self._source_conf(source_name)
        module, prefix = self._backend(conf["TYPE"])
        cls = getattr(module, f"{prefix}{dao_name}", None)
        if cls is None:
            raise StorageError(
                f"backend {conf['TYPE']!r} does not implement {dao_name}"
            )
        return self._client(source_name).dao(cls, namespace)

    def _repo_object(self, repo: str, dao_name: str):
        r = self._repos[repo]
        return self.get_data_object(r["SOURCE"], r.get("NAME", "pio"), dao_name)

    # --- public accessors (reference Storage.scala:350-384) ---

    def repository_type(self, repo: str) -> str:
        """Backend TYPE behind a repository (e.g. 'memory', 'sqlite',
        'http') — lets callers reason about sharing semantics (a
        multi-process deployment needs a multi-process-shared store)."""
        r = self._repos.get(repo.upper())
        if r is None or "SOURCE" not in r:
            raise StorageError(f"repository {repo} is not configured")
        return self._source_conf(r["SOURCE"])["TYPE"]

    def get_l_events(self):
        return self._repo_object("EVENTDATA", "LEvents")

    # the reference splits local/parallel event access (getLEvents/getPEvents);
    # in the single-controller runtime both roles are served by one DAO
    get_p_events = get_l_events

    def get_meta_data_apps(self) -> Apps:
        return self._repo_object("METADATA", "Apps")

    def get_meta_data_access_keys(self) -> AccessKeys:
        return self._repo_object("METADATA", "AccessKeys")

    def get_meta_data_channels(self) -> Channels:
        return self._repo_object("METADATA", "Channels")

    def get_meta_data_engine_manifests(self) -> EngineManifests:
        return self._repo_object("METADATA", "EngineManifests")

    def get_meta_data_engine_instances(self) -> EngineInstances:
        return self._repo_object("METADATA", "EngineInstances")

    def get_meta_data_evaluation_instances(self) -> EvaluationInstances:
        return self._repo_object("METADATA", "EvaluationInstances")

    def get_model_data_models(self) -> Models:
        return self._repo_object("MODELDATA", "Models")

    # --- smoke probe (reference verifyAllDataObjects, Storage.scala:325-348) ---

    def verify_all_data_objects(self) -> bool:
        self.get_meta_data_apps()
        self.get_meta_data_access_keys()
        self.get_meta_data_channels()
        self.get_meta_data_engine_manifests()
        self.get_meta_data_engine_instances()
        self.get_meta_data_evaluation_instances()
        self.get_model_data_models()
        events = self.get_l_events()
        events.init(0)
        events.insert(
            __import__(
                "predictionio_tpu.data.event", fromlist=["Event"]
            ).Event(event="$set", entity_type="pio_pr", entity_id="0"),
            0,
        )
        events.remove(0)
        return True

    def repositories(self) -> Dict[str, Dict[str, str]]:
        return {k: dict(v) for k, v in self._repos.items()}

    def sources(self) -> Dict[str, Dict[str, str]]:
        return {k: dict(v) for k, v in self._sources.items()}


# --- module-level default instance (lazy, resettable for tests) ---

_default: Optional[Storage] = None
_default_lock = threading.Lock()


def get_storage() -> Storage:
    global _default
    with _default_lock:
        if _default is None:
            _default = Storage()
        return _default


def set_storage(storage: Optional[Storage]) -> None:
    """Install (or clear, with None) the process-default Storage. Tests use
    this to point the framework at a fresh in-memory universe."""
    global _default
    with _default_lock:
        _default = storage


def memory_storage() -> Storage:
    """A fresh, fully in-memory storage universe."""
    return Storage(dict(MEMORY_CONFIG))
