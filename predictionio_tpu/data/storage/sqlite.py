"""SQLite storage backend — the persistent embedded default.

Plays the role of the reference's JDBC backend
(data/src/main/scala/io/prediction/data/storage/jdbc/): one database file
holds the metadata tables and per-app/channel event tables named
``events_<app>[_<channel>]`` (the reference's table-per-app/channel scheme,
JDBCUtils/HBEventsUtil). Event rows carry a millisecond timestamp column for
ordered range scans (the role of the HBase row-key time component,
hbase/HBEventsUtil.scala:82-130).
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import json
import os

from predictionio_tpu.utils.fs import fs_basedir
import sqlite3
import threading
from typing import Dict, Iterator, List, Optional, Sequence

from predictionio_tpu.data.event import (
    DataMap,
    Event,
    format_iso8601,
    new_event_id,
    parse_iso8601,
)
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import (
    UNSET,
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EngineManifest,
    EvaluationInstance,
    Model,
    OptFilter,
    StorageError,
)


def _ms(t: _dt.datetime) -> int:
    if t.tzinfo is None:
        t = t.replace(tzinfo=_dt.timezone.utc)
    return int(t.timestamp() * 1000)


def _utc_iso(t: _dt.datetime) -> str:
    """UTC-normalized fixed-width ISO8601, so lexicographic TEXT ordering is
    chronological (used for instance start/end times in ORDER BY)."""
    if t.tzinfo is None:
        t = t.replace(tzinfo=_dt.timezone.utc)
    return format_iso8601(t.astimezone(_dt.timezone.utc))


class _LockedCursor:
    """Runs a statement under the client lock and materializes results, so
    concurrent REST worker threads never interleave cursor state on the
    shared connection."""

    __slots__ = ("_rows", "rowcount", "lastrowid")

    def __init__(self, client: "StorageClient", sql: str, params=()):
        with client.lock:
            cur = client.conn.execute(sql, params)
            self._rows = cur.fetchall() if cur.description is not None else []
            self.rowcount = cur.rowcount
            self.lastrowid = cur.lastrowid

    def fetchone(self):
        return self._rows[0] if self._rows else None

    def fetchall(self):
        return self._rows


class StorageClient(base.DAOCacheMixin):
    """Shared sqlite connection per source (reference caches clients per
    source name, Storage.scala:202-208). ``check_same_thread=False`` plus a
    lock serializes access from REST worker threads."""

    def __init__(self, config=None):
        self.config = config
        props = getattr(config, "properties", {}) or {}
        path = props.get("PATH") or os.path.join(
            fs_basedir(),
            "storage.db",
        )
        if path != ":memory:":
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self.path = path
        self.conn = sqlite3.connect(path, check_same_thread=False)
        self.conn.execute("PRAGMA journal_mode=WAL")
        self.lock = threading.RLock()
        self._init_dao_cache(self.lock)

    def execute(self, sql: str, params=()) -> _LockedCursor:
        return _LockedCursor(self, sql, params)

    def commit(self) -> None:
        with self.lock:
            self.conn.commit()

def _table_name(namespace: str, suffix: str) -> str:
    ns = "".join(c if c.isalnum() else "_" for c in (namespace or "pio"))
    return f"{ns}_{suffix}"


class SQLiteLEvents(base.LEvents):
    def __init__(self, client: StorageClient, config=None, namespace: str = ""):
        self._c = client
        self._ns = namespace or "pio"

    def _events_table(self, app_id: int, channel_id: Optional[int]) -> str:
        name = _table_name(self._ns, f"events_{int(app_id)}")
        if channel_id is not None:
            name += f"_{int(channel_id)}"
        return name

    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        t = self._events_table(app_id, channel_id)
        with self._c.lock:
            self._c.execute(
                f"""CREATE TABLE IF NOT EXISTS {t} (
                    id TEXT PRIMARY KEY,
                    event TEXT NOT NULL,
                    entity_type TEXT NOT NULL,
                    entity_id TEXT NOT NULL,
                    target_entity_type TEXT,
                    target_entity_id TEXT,
                    properties TEXT,
                    event_time TEXT NOT NULL,
                    event_time_ms INTEGER NOT NULL,
                    tags TEXT,
                    pr_id TEXT,
                    creation_time TEXT NOT NULL
                )"""
            )
            self._c.execute(
                f"CREATE INDEX IF NOT EXISTS {t}_time ON {t} (event_time_ms)"
            )
            self._c.execute(
                f"CREATE INDEX IF NOT EXISTS {t}_entity ON {t} "
                f"(entity_type, entity_id, event_time_ms)"
            )
            self._c.commit()
        return True

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        t = self._events_table(app_id, channel_id)
        with self._c.lock:
            self._c.execute(f"DROP TABLE IF EXISTS {t}")
            self._c.commit()
        return True

    def close(self) -> None:
        pass

    def _exists(self, table: str) -> bool:
        cur = self._c.execute(
            "SELECT name FROM sqlite_master WHERE type='table' AND name=?", (table,)
        )
        return cur.fetchone() is not None

    def insert(self, event: Event, app_id: int, channel_id: Optional[int] = None) -> str:
        t = self._events_table(app_id, channel_id)
        eid = event.event_id or new_event_id()
        with self._c.lock:
            if not self._exists(t):
                raise StorageError(f"events table {t} not initialized")
            self._c.execute(
                f"INSERT OR REPLACE INTO {t} VALUES (?,?,?,?,?,?,?,?,?,?,?,?)",
                (
                    eid,
                    event.event,
                    event.entity_type,
                    event.entity_id,
                    event.target_entity_type,
                    event.target_entity_id,
                    json.dumps(event.properties.to_json()),
                    format_iso8601(event.event_time),
                    _ms(event.event_time),
                    json.dumps(list(event.tags)),
                    event.pr_id,
                    format_iso8601(event.creation_time),
                ),
            )
            self._c.commit()
        return eid

    @staticmethod
    def _row_to_event(row) -> Event:
        return Event(
            event_id=row[0],
            event=row[1],
            entity_type=row[2],
            entity_id=row[3],
            target_entity_type=row[4],
            target_entity_id=row[5],
            properties=DataMap(json.loads(row[6]) if row[6] else {}),
            event_time=parse_iso8601(row[7]),
            tags=tuple(json.loads(row[9]) if row[9] else ()),
            pr_id=row[10],
            creation_time=parse_iso8601(row[11]),
        )

    def get(
        self, event_id: str, app_id: int, channel_id: Optional[int] = None
    ) -> Optional[Event]:
        t = self._events_table(app_id, channel_id)
        with self._c.lock:
            if not self._exists(t):
                raise StorageError(f"events table {t} not initialized")
            cur = self._c.execute(f"SELECT * FROM {t} WHERE id=?", (event_id,))
            row = cur.fetchone()
        return self._row_to_event(row) if row else None

    def delete(
        self, event_id: str, app_id: int, channel_id: Optional[int] = None
    ) -> bool:
        t = self._events_table(app_id, channel_id)
        with self._c.lock:
            if not self._exists(t):
                raise StorageError(f"events table {t} not initialized")
            cur = self._c.execute(f"DELETE FROM {t} WHERE id=?", (event_id,))
            self._c.commit()
            return cur.rowcount > 0

    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: OptFilter = UNSET,
        target_entity_id: OptFilter = UNSET,
        limit: Optional[int] = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        t = self._events_table(app_id, channel_id)
        clauses: List[str] = []
        params: list = []
        if start_time is not None:
            clauses.append("event_time_ms >= ?")
            params.append(_ms(start_time))
        if until_time is not None:
            clauses.append("event_time_ms < ?")
            params.append(_ms(until_time))
        if entity_type is not None:
            clauses.append("entity_type = ?")
            params.append(entity_type)
        if entity_id is not None:
            clauses.append("entity_id = ?")
            params.append(entity_id)
        if event_names is not None:
            if event_names:
                clauses.append(
                    "event IN (" + ",".join("?" * len(event_names)) + ")"
                )
                params.extend(event_names)
            else:
                clauses.append("1=0")  # empty allow-list matches nothing
        if target_entity_type is not UNSET:
            if target_entity_type is None:
                clauses.append("target_entity_type IS NULL")
            else:
                clauses.append("target_entity_type = ?")
                params.append(target_entity_type)
        if target_entity_id is not UNSET:
            if target_entity_id is None:
                clauses.append("target_entity_id IS NULL")
            else:
                clauses.append("target_entity_id = ?")
                params.append(target_entity_id)
        sql = f"SELECT * FROM {t}"
        if clauses:
            sql += " WHERE " + " AND ".join(clauses)
        sql += f" ORDER BY event_time_ms {'DESC' if reversed else 'ASC'}"
        if limit is not None and limit >= 0:
            sql += f" LIMIT {int(limit)}"
        with self._c.lock:
            if not self._exists(t):
                raise StorageError(f"events table {t} not initialized")
            rows = self._c.execute(sql, params).fetchall()
        return (self._row_to_event(r) for r in rows)


class _SQLiteMetaBase:
    def __init__(self, client: StorageClient, config=None, namespace: str = ""):
        self._c = client
        self._ns = namespace or "pio"
        with self._c.lock:
            self._create()
            self._c.commit()

    def _t(self, suffix: str) -> str:
        return _table_name(self._ns, suffix)

    def _create(self) -> None:
        raise NotImplementedError


class SQLiteApps(_SQLiteMetaBase, base.Apps):
    def _create(self):
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {self._t('apps')} (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                name TEXT NOT NULL UNIQUE,
                description TEXT)"""
        )

    def insert(self, app: App) -> Optional[int]:
        with self._c.lock:
            try:
                if app.id:
                    cur = self._c.execute(
                        f"INSERT INTO {self._t('apps')} (id,name,description) VALUES (?,?,?)",
                        (app.id, app.name, app.description),
                    )
                else:
                    cur = self._c.execute(
                        f"INSERT INTO {self._t('apps')} (name,description) VALUES (?,?)",
                        (app.name, app.description),
                    )
                self._c.commit()
                return cur.lastrowid if not app.id else app.id
            except sqlite3.IntegrityError:
                return None

    def get(self, app_id: int) -> Optional[App]:
        row = self._c.execute(
            f"SELECT id,name,description FROM {self._t('apps')} WHERE id=?", (app_id,)
        ).fetchone()
        return App(*row) if row else None

    def get_by_name(self, name: str) -> Optional[App]:
        row = self._c.execute(
            f"SELECT id,name,description FROM {self._t('apps')} WHERE name=?", (name,)
        ).fetchone()
        return App(*row) if row else None

    def get_all(self) -> List[App]:
        rows = self._c.execute(
            f"SELECT id,name,description FROM {self._t('apps')} ORDER BY id"
        ).fetchall()
        return [App(*r) for r in rows]

    def update(self, app: App) -> bool:
        with self._c.lock:
            cur = self._c.execute(
                f"UPDATE {self._t('apps')} SET name=?,description=? WHERE id=?",
                (app.name, app.description, app.id),
            )
            self._c.commit()
            return cur.rowcount > 0

    def delete(self, app_id: int) -> bool:
        with self._c.lock:
            cur = self._c.execute(
                f"DELETE FROM {self._t('apps')} WHERE id=?", (app_id,)
            )
            self._c.commit()
            return cur.rowcount > 0


class SQLiteAccessKeys(_SQLiteMetaBase, base.AccessKeys):
    def _create(self):
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {self._t('access_keys')} (
                key TEXT PRIMARY KEY, appid INTEGER NOT NULL, events TEXT)"""
        )

    def insert(self, access_key: AccessKey) -> Optional[str]:
        key = access_key.key or self.generate_key()
        with self._c.lock:
            try:
                self._c.execute(
                    f"INSERT INTO {self._t('access_keys')} VALUES (?,?,?)",
                    (key, access_key.appid, json.dumps(list(access_key.events))),
                )
                self._c.commit()
                return key
            except sqlite3.IntegrityError:
                return None

    @staticmethod
    def _row(row) -> AccessKey:
        return AccessKey(row[0], row[1], tuple(json.loads(row[2] or "[]")))

    def get(self, key: str) -> Optional[AccessKey]:
        row = self._c.execute(
            f"SELECT * FROM {self._t('access_keys')} WHERE key=?", (key,)
        ).fetchone()
        return self._row(row) if row else None

    def get_all(self) -> List[AccessKey]:
        return [
            self._row(r)
            for r in self._c.execute(
                f"SELECT * FROM {self._t('access_keys')}"
            ).fetchall()
        ]

    def get_by_app_id(self, app_id: int) -> List[AccessKey]:
        return [
            self._row(r)
            for r in self._c.execute(
                f"SELECT * FROM {self._t('access_keys')} WHERE appid=?", (app_id,)
            ).fetchall()
        ]

    def update(self, access_key: AccessKey) -> bool:
        with self._c.lock:
            cur = self._c.execute(
                f"UPDATE {self._t('access_keys')} SET appid=?,events=? WHERE key=?",
                (access_key.appid, json.dumps(list(access_key.events)), access_key.key),
            )
            self._c.commit()
            return cur.rowcount > 0

    def delete(self, key: str) -> bool:
        with self._c.lock:
            cur = self._c.execute(
                f"DELETE FROM {self._t('access_keys')} WHERE key=?", (key,)
            )
            self._c.commit()
            return cur.rowcount > 0


class SQLiteChannels(_SQLiteMetaBase, base.Channels):
    def _create(self):
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {self._t('channels')} (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                name TEXT NOT NULL, appid INTEGER NOT NULL)"""
        )

    def insert(self, channel: Channel) -> Optional[int]:
        if not Channel.is_valid_name(channel.name):
            return None
        with self._c.lock:
            if channel.id:
                self._c.execute(
                    f"INSERT INTO {self._t('channels')} (id,name,appid) VALUES (?,?,?)",
                    (channel.id, channel.name, channel.appid),
                )
                cid = channel.id
            else:
                cur = self._c.execute(
                    f"INSERT INTO {self._t('channels')} (name,appid) VALUES (?,?)",
                    (channel.name, channel.appid),
                )
                cid = cur.lastrowid
            self._c.commit()
            return cid

    def get(self, channel_id: int) -> Optional[Channel]:
        row = self._c.execute(
            f"SELECT id,name,appid FROM {self._t('channels')} WHERE id=?",
            (channel_id,),
        ).fetchone()
        return Channel(*row) if row else None

    def get_by_app_id(self, app_id: int) -> List[Channel]:
        rows = self._c.execute(
            f"SELECT id,name,appid FROM {self._t('channels')} WHERE appid=?",
            (app_id,),
        ).fetchall()
        return [Channel(*r) for r in rows]

    def delete(self, channel_id: int) -> bool:
        with self._c.lock:
            cur = self._c.execute(
                f"DELETE FROM {self._t('channels')} WHERE id=?", (channel_id,)
            )
            self._c.commit()
            return cur.rowcount > 0


class SQLiteEngineManifests(_SQLiteMetaBase, base.EngineManifests):
    def _create(self):
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {self._t('engine_manifests')} (
                id TEXT, version TEXT, name TEXT, description TEXT,
                files TEXT, engine_factory TEXT,
                PRIMARY KEY (id, version))"""
        )

    def insert(self, manifest: EngineManifest) -> None:
        self.update(manifest, upsert=True)

    def get(self, id: str, version: str) -> Optional[EngineManifest]:
        row = self._c.execute(
            f"SELECT * FROM {self._t('engine_manifests')} WHERE id=? AND version=?",
            (id, version),
        ).fetchone()
        if not row:
            return None
        return EngineManifest(
            row[0], row[1], row[2], row[3], tuple(json.loads(row[4] or "[]")), row[5]
        )

    def get_all(self) -> List[EngineManifest]:
        rows = self._c.execute(
            f"SELECT * FROM {self._t('engine_manifests')}"
        ).fetchall()
        return [
            EngineManifest(r[0], r[1], r[2], r[3], tuple(json.loads(r[4] or "[]")), r[5])
            for r in rows
        ]

    def update(self, manifest: EngineManifest, upsert: bool = False) -> None:
        with self._c.lock:
            self._c.execute(
                f"INSERT OR REPLACE INTO {self._t('engine_manifests')} VALUES (?,?,?,?,?,?)",
                (
                    manifest.id,
                    manifest.version,
                    manifest.name,
                    manifest.description,
                    json.dumps(list(manifest.files)),
                    manifest.engine_factory,
                ),
            )
            self._c.commit()

    def delete(self, id: str, version: str) -> None:
        with self._c.lock:
            self._c.execute(
                f"DELETE FROM {self._t('engine_manifests')} WHERE id=? AND version=?",
                (id, version),
            )
            self._c.commit()


_EI_COLS = (
    "id, status, start_time, end_time, engine_id, engine_version, "
    "engine_variant, engine_factory, batch, env, spark_conf, "
    "data_source_params, preparator_params, algorithms_params, serving_params"
)


class SQLiteEngineInstances(_SQLiteMetaBase, base.EngineInstances):
    def _create(self):
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {self._t('engine_instances')} (
                id TEXT PRIMARY KEY, status TEXT, start_time TEXT, end_time TEXT,
                engine_id TEXT, engine_version TEXT, engine_variant TEXT,
                engine_factory TEXT, batch TEXT, env TEXT, spark_conf TEXT,
                data_source_params TEXT, preparator_params TEXT,
                algorithms_params TEXT, serving_params TEXT)"""
        )

    @staticmethod
    def _row(r) -> EngineInstance:
        return EngineInstance(
            id=r[0],
            status=r[1],
            start_time=parse_iso8601(r[2]),
            end_time=parse_iso8601(r[3]),
            engine_id=r[4],
            engine_version=r[5],
            engine_variant=r[6],
            engine_factory=r[7],
            batch=r[8] or "",
            env=json.loads(r[9] or "{}"),
            spark_conf=json.loads(r[10] or "{}"),
            data_source_params=r[11] or "",
            preparator_params=r[12] or "",
            algorithms_params=r[13] or "",
            serving_params=r[14] or "",
        )

    def _write(self, i: EngineInstance) -> None:
        self._c.execute(
            f"INSERT OR REPLACE INTO {self._t('engine_instances')} "
            f"VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
            (
                i.id,
                i.status,
                _utc_iso(i.start_time),
                _utc_iso(i.end_time),
                i.engine_id,
                i.engine_version,
                i.engine_variant,
                i.engine_factory,
                i.batch,
                json.dumps(i.env),
                json.dumps(i.spark_conf),
                i.data_source_params,
                i.preparator_params,
                i.algorithms_params,
                i.serving_params,
            ),
        )

    def insert(self, instance: EngineInstance) -> str:
        import uuid

        iid = instance.id or uuid.uuid4().hex[:17]
        with self._c.lock:
            self._write(dataclasses.replace(instance, id=iid))
            self._c.commit()
        return iid

    def get(self, id: str) -> Optional[EngineInstance]:
        row = self._c.execute(
            f"SELECT {_EI_COLS} FROM {self._t('engine_instances')} WHERE id=?", (id,)
        ).fetchone()
        return self._row(row) if row else None

    def get_all(self) -> List[EngineInstance]:
        rows = self._c.execute(
            f"SELECT {_EI_COLS} FROM {self._t('engine_instances')}"
        ).fetchall()
        return [self._row(r) for r in rows]

    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> List[EngineInstance]:
        rows = self._c.execute(
            f"SELECT {_EI_COLS} FROM {self._t('engine_instances')} "
            "WHERE status=? AND engine_id=? AND engine_version=? AND engine_variant=? "
            "ORDER BY start_time DESC",
            (base.STATUS_COMPLETED, engine_id, engine_version, engine_variant),
        ).fetchall()
        return [self._row(r) for r in rows]

    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> Optional[EngineInstance]:
        out = self.get_completed(engine_id, engine_version, engine_variant)
        return out[0] if out else None

    def update(self, instance: EngineInstance) -> None:
        with self._c.lock:
            self._write(instance)
            self._c.commit()

    def delete(self, id: str) -> None:
        with self._c.lock:
            self._c.execute(
                f"DELETE FROM {self._t('engine_instances')} WHERE id=?", (id,)
            )
            self._c.commit()


class SQLiteEvaluationInstances(_SQLiteMetaBase, base.EvaluationInstances):
    def _create(self):
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {self._t('evaluation_instances')} (
                id TEXT PRIMARY KEY, status TEXT, start_time TEXT, end_time TEXT,
                evaluation_class TEXT, engine_params_generator_class TEXT,
                batch TEXT, env TEXT, spark_conf TEXT,
                evaluator_results TEXT, evaluator_results_html TEXT,
                evaluator_results_json TEXT)"""
        )

    @staticmethod
    def _row(r) -> EvaluationInstance:
        return EvaluationInstance(
            id=r[0],
            status=r[1],
            start_time=parse_iso8601(r[2]),
            end_time=parse_iso8601(r[3]),
            evaluation_class=r[4] or "",
            engine_params_generator_class=r[5] or "",
            batch=r[6] or "",
            env=json.loads(r[7] or "{}"),
            spark_conf=json.loads(r[8] or "{}"),
            evaluator_results=r[9] or "",
            evaluator_results_html=r[10] or "",
            evaluator_results_json=r[11] or "",
        )

    def _write(self, i: EvaluationInstance) -> None:
        self._c.execute(
            f"INSERT OR REPLACE INTO {self._t('evaluation_instances')} "
            f"VALUES (?,?,?,?,?,?,?,?,?,?,?,?)",
            (
                i.id,
                i.status,
                _utc_iso(i.start_time),
                _utc_iso(i.end_time),
                i.evaluation_class,
                i.engine_params_generator_class,
                i.batch,
                json.dumps(i.env),
                json.dumps(i.spark_conf),
                i.evaluator_results,
                i.evaluator_results_html,
                i.evaluator_results_json,
            ),
        )

    def insert(self, instance: EvaluationInstance) -> str:
        import uuid

        iid = instance.id or uuid.uuid4().hex[:17]
        with self._c.lock:
            self._write(dataclasses.replace(instance, id=iid))
            self._c.commit()
        return iid

    def get(self, id: str) -> Optional[EvaluationInstance]:
        row = self._c.execute(
            f"SELECT * FROM {self._t('evaluation_instances')} WHERE id=?", (id,)
        ).fetchone()
        return self._row(row) if row else None

    def get_all(self) -> List[EvaluationInstance]:
        rows = self._c.execute(
            f"SELECT * FROM {self._t('evaluation_instances')}"
        ).fetchall()
        return [self._row(r) for r in rows]

    def get_completed(self) -> List[EvaluationInstance]:
        rows = self._c.execute(
            f"SELECT * FROM {self._t('evaluation_instances')} "
            "WHERE status=? ORDER BY start_time DESC",
            (base.STATUS_COMPLETED,),
        ).fetchall()
        return [self._row(r) for r in rows]

    def update(self, instance: EvaluationInstance) -> None:
        with self._c.lock:
            self._write(instance)
            self._c.commit()

    def delete(self, id: str) -> None:
        with self._c.lock:
            self._c.execute(
                f"DELETE FROM {self._t('evaluation_instances')} WHERE id=?", (id,)
            )
            self._c.commit()


class SQLiteModels(_SQLiteMetaBase, base.Models):
    def _create(self):
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {self._t('models')} (
                id TEXT PRIMARY KEY, models BLOB)"""
        )

    def insert(self, model: Model) -> None:
        with self._c.lock:
            self._c.execute(
                f"INSERT OR REPLACE INTO {self._t('models')} VALUES (?,?)",
                (model.id, model.models),
            )
            self._c.commit()

    def get(self, id: str) -> Optional[Model]:
        row = self._c.execute(
            f"SELECT id, models FROM {self._t('models')} WHERE id=?", (id,)
        ).fetchone()
        return Model(row[0], row[1]) if row else None

    def delete(self, id: str) -> None:
        with self._c.lock:
            self._c.execute(
                f"DELETE FROM {self._t('models')} WHERE id=?", (id,)
            )
            self._c.commit()
