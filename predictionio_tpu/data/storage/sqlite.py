"""SQLite storage backend — the persistent embedded default.

Plays the role of the reference's JDBC backend
(data/src/main/scala/io/prediction/data/storage/jdbc/): one database file
holds the metadata tables and per-app/channel event tables named
``events_<app>[_<channel>]`` (the reference's table-per-app/channel scheme,
JDBCUtils/HBEventsUtil). Event rows carry a millisecond timestamp column for
ordered range scans (the role of the HBase row-key time component,
hbase/HBEventsUtil.scala:82-130).

Write-path scale-out (the role of the reference's HBase region servers):

- **Group commit.** Single-event inserts do not commit their own
  transaction. REST worker threads enqueue rows onto a bounded per-shard
  queue; a committer thread per shard coalesces queued rows into ONE
  multi-row transaction (flush at ``GROUP_COMMIT_EVENTS`` rows or
  ``GROUP_COMMIT_MS`` after the batch opened, whichever first — a solo
  row with an idle queue flushes immediately). The caller's ``insert``
  returns only after its batch's COMMIT, so the 201 ack still means
  durable-to-WAL; what changes is that N concurrent inserts now cost one
  commit instead of N.

- **Hash sharding.** With ``PIO_STORAGE_SOURCES_<NAME>_SHARDS = K`` (>1),
  single-event rows split across K independent sqlite files
  (``<path>.shard<k>``) by a stable hash of the entity id. Each shard has
  its own connection, lock, WAL write slot, and committer — concurrent
  writers stop serializing on one lock. The main file keeps the metadata
  tables, the columnar page store, and the (possibly pre-sharding) row
  table, which participates in every scan as shard "-1"; turning shards
  on for an existing database is therefore seamless. Events of one
  entity always land in one shard, so per-entity order is preserved and
  the streaming scan's counting-sort merge reproduces the single-file
  wire byte-for-byte (``ops/streaming.py``).
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import json
import logging
import os
import queue as _queue
import time as _time
import zlib

from predictionio_tpu.utils.fs import fs_basedir
import sqlite3
import threading
from typing import Dict, Iterator, List, Optional, Sequence

from predictionio_tpu.data.event import (
    DataMap,
    Event,
    format_iso8601,
    new_event_id,
    parse_iso8601,
)
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import (
    UNSET,
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EngineManifest,
    EvaluationInstance,
    Model,
    OptFilter,
    PartialBatchError,
    StorageError,
)


logger = logging.getLogger(__name__)


def _ms(t: _dt.datetime) -> int:
    if t.tzinfo is None:
        t = t.replace(tzinfo=_dt.timezone.utc)
    return int(t.timestamp() * 1000)


def _utc_iso(t: _dt.datetime) -> str:
    """UTC-normalized fixed-width ISO8601, so lexicographic TEXT ordering is
    chronological (used for instance start/end times in ORDER BY)."""
    if t.tzinfo is None:
        t = t.replace(tzinfo=_dt.timezone.utc)
    return format_iso8601(t.astimezone(_dt.timezone.utc))


class _LockedCursor:
    """Runs a statement under the client lock and materializes results, so
    concurrent REST worker threads never interleave cursor state on the
    shared connection."""

    __slots__ = ("_rows", "rowcount", "lastrowid")

    def __init__(self, client: "StorageClient", sql: str, params=()):
        with client.lock:
            cur = client.conn.execute(sql, params)
            self._rows = cur.fetchall() if cur.description is not None else []
            self.rowcount = cur.rowcount
            self.lastrowid = cur.lastrowid

    def fetchone(self):
        return self._rows[0] if self._rows else None

    def fetchall(self):
        return self._rows


def _open_wal_conn(path: str) -> sqlite3.Connection:
    """Open a writer connection in the mode every concurrent path here
    assumes: WAL (readers on other connections see a consistent snapshot
    while one writer proceeds), busy_timeout for multi-process writers
    (gateway + CLI) briefly contending for the single WAL write slot, and
    synchronous=NORMAL — WAL's standard production pairing: commits
    append to the WAL without an fsync each (integrity is preserved on
    crash; only the tail of very recent commits may be lost on power
    failure). Per-event REST ingest is commit-bound — FULL measured ~380
    events/s vs ~thousands with NORMAL on the same rig."""
    conn = sqlite3.connect(path, check_same_thread=False)
    conn.execute("PRAGMA journal_mode=WAL")
    conn.execute("PRAGMA busy_timeout=5000")
    conn.execute("PRAGMA synchronous=NORMAL")
    return conn


class _InsertUnit:
    """One atomic slice of committer work: a statement plus the rows to
    executemany it with. All rows of a unit commit together or not at
    all — a unit is one REST insert (1 row) or one ``insert_batch`` slice
    (the ``/batch/events.json`` group), so a reader can never observe a
    torn unit."""

    __slots__ = ("sql", "rows", "error", "done")

    def __init__(self, sql: str, rows: list):
        self.sql = sql
        self.rows = rows
        self.error: Optional[BaseException] = None
        self.done = threading.Event()

    # generous: a unit is at most one committer flush (~512 rows), but
    # it may queue behind a full backlog on a slow disk — this bound
    # exists to surface a wedged committer, not to deadline healthy I/O
    WAIT_S = 600.0

    def wait(self, timeout: Optional[float] = None) -> None:
        if not self.done.wait(self.WAIT_S if timeout is None else timeout):
            # the unit is NOT cancelled — it may still commit after this
            # raises, so the outcome is unknown, not "failed": a caller
            # that blind-retries could duplicate the event
            raise StorageError(
                "group-commit writer did not resolve within "
                f"{self.WAIT_S if timeout is None else timeout}s; "
                "outcome UNKNOWN (the batch may still commit) — "
                "investigate the committer before retrying"
            )
        if self.error is not None:
            raise self.error


class _GroupCommitter:
    """Per-shard group-commit thread: worker threads enqueue
    :class:`_InsertUnit`s on a bounded queue; this thread coalesces them
    into one multi-row transaction. Flush policy: at ``max_rows`` rows or
    ``max_delay_s`` after the batch opened, whichever first; a solo unit
    with an idle queue flushes immediately, so sequential callers pay no
    accumulation latency — batching kicks in exactly when concurrency
    exists. Callers block on ``unit.wait()``, so their ack still means
    the rows are committed (durable to the WAL)."""

    _STOP = object()

    def __init__(self, shard: "_ShardState", max_rows: int, max_delay_s: float):
        self._shard = shard
        self._max_rows = max(1, int(max_rows))
        self._max_delay_s = max(0.0, float(max_delay_s))
        self._q: "_queue.Queue[_InsertUnit]" = _queue.Queue(maxsize=4096)
        self._thread: Optional[threading.Thread] = None
        self._start_lock = threading.Lock()

    def close(self, timeout: float = 10.0) -> None:
        """Drain-and-stop: queued units ahead of the sentinel still
        commit, then the thread exits. Idempotent; a never-started
        committer has nothing to stop."""
        t = self._thread
        if t is None or not t.is_alive():
            return
        self._q.put(self._STOP)
        t.join(timeout)

    def submit(self, sql: str, rows: list) -> _InsertUnit:
        unit = _InsertUnit(sql, rows)
        if self._thread is None:
            with self._start_lock:
                if self._thread is None:
                    t = threading.Thread(
                        target=self._run, daemon=True,
                        name="sqlite-group-commit",
                    )
                    t.start()
                    self._thread = t
        self._q.put(unit)
        return unit

    def _run(self) -> None:
        while True:
            try:
                if not self._drain_one_batch():
                    return  # close() sentinel
            except BaseException:  # the loop must survive anything —
                # but never silently: an exception here (outside
                # _commit_batch's own handling) means some units may
                # never resolve and their callers will time out
                logger.exception(
                    "group-commit loop error; queued units may be lost"
                )
                continue

    def _drain_one_batch(self) -> bool:
        unit = self._q.get()
        if unit is self._STOP:
            return False
        batch = [unit]
        n = len(unit.rows)
        deadline = _time.monotonic() + self._max_delay_s
        while n < self._max_rows:
            try:
                nxt = self._q.get_nowait()
            except _queue.Empty:
                if len(batch) == 1:
                    break  # solo unit, idle queue: zero added latency
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=remaining)
                except _queue.Empty:
                    break
            if nxt is self._STOP:
                self._q.put(nxt)  # commit this batch, stop next round
                break
            batch.append(nxt)
            n += len(nxt.rows)
        self._commit_batch(batch)
        return True

    def _commit_batch(self, batch: list) -> None:
        shard = self._shard
        with shard.lock:
            try:
                for u in batch:
                    shard.conn.executemany(u.sql, u.rows)
                fault = shard.commit_fault  # test-only crash injection
                if fault is not None:
                    fault()
                shard.conn.commit()
            except BaseException as e:
                try:
                    shard.conn.rollback()
                except sqlite3.Error:
                    pass
                if len(batch) == 1:
                    batch[0].error = e
                else:
                    # poison isolation: replay each unit as its own
                    # transaction so one bad unit cannot fail its
                    # coalesced neighbors; each replay stays unit-atomic
                    # and consults the fault hook too, so crash tests
                    # can abort coalesced batches, not just solo units
                    for u in batch:
                        try:
                            shard.conn.executemany(u.sql, u.rows)
                            fault = shard.commit_fault
                            if fault is not None:
                                fault()
                            shard.conn.commit()
                        except BaseException as ue:
                            try:
                                shard.conn.rollback()
                            except sqlite3.Error:
                                pass
                            u.error = ue
            finally:
                for u in batch:
                    u.done.set()


class _ShardState:
    """One event-row write slot: a sqlite connection, its lock, its
    thread-local WAL snapshot read connections, and its group committer.
    The main database file is wrapped in one of these (sharing the
    client's connection and lock); with ``SHARDS`` > 1, each shard file
    gets an independent one — an independent WAL write slot."""

    def __init__(
        self,
        path: str,
        conn: sqlite3.Connection,
        lock,
        gc_rows: int,
        gc_delay_s: float,
    ):
        self.path = path
        self.conn = conn
        self.lock = lock
        self._read_local = threading.local()
        # memoized POSITIVE table-existence results (see _exists_memo)
        self.known_tables: set = set()
        # test-only fault injection: called between the batch's last
        # execute and its COMMIT (crash-consistency tests)
        self.commit_fault = None
        self.committer = _GroupCommitter(self, gc_rows, gc_delay_s)

    @staticmethod
    def open(path: str, gc_rows: int, gc_delay_s: float) -> "_ShardState":
        return _ShardState(
            path, _open_wal_conn(path), threading.RLock(), gc_rows,
            gc_delay_s,
        )

    def execute(self, sql: str, params=()) -> _LockedCursor:
        return _LockedCursor(self, sql, params)

    def commit(self) -> None:
        with self.lock:
            self.conn.commit()

    def read_execute(self, sql: str, params=()):
        """Run a read-only statement on a thread-local WAL connection —
        no writer lock held, so long scans and concurrent writes overlap.
        Returns a live cursor (fetchone/fetchall). :memory: databases are
        not shareable across connections and fall back to the locked
        shared connection.

        Because the existence check and the read no longer share one lock
        scope, a concurrent table drop (app delete) can surface here as
        sqlite's raw OperationalError — it is re-raised as StorageError so
        read paths keep their documented error contract."""
        if self.path == ":memory:":
            return self.execute(sql, params)
        conn = getattr(self._read_local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self.path)
            conn.execute("PRAGMA busy_timeout=5000")
            conn.execute("PRAGMA query_only=ON")
            self._read_local.conn = conn
        try:
            return conn.execute(sql, params)
        except sqlite3.OperationalError as e:
            if "no such table" in str(e):
                raise StorageError(str(e)) from e
            raise

    def has_table(self, table: str) -> bool:
        """Memoized (positive results only) existence probe against THIS
        shard's file; a table created later must be seen, so negatives
        re-probe."""
        if table in self.known_tables:
            return True
        row = self.execute(
            "SELECT name FROM sqlite_master WHERE type='table' AND name=?",
            (table,),
        ).fetchone()
        if row is not None:
            self.known_tables.add(table)
            return True
        return False

    def submit_rows(self, sql: str, rows: list) -> _InsertUnit:
        """Hand rows to the group committer; returns the unit to wait
        on. The caller sees the commit (or the unit's error) via
        ``unit.wait()``."""
        return self.committer.submit(sql, rows)


class StorageClient(base.DAOCacheMixin):
    """Shared sqlite connection per source (reference caches clients per
    source name, Storage.scala:202-208). ``check_same_thread=False`` plus a
    lock serializes WRITE access from REST worker threads; bulk reads run
    on per-thread WAL snapshot connections (``read_execute``), so a
    training scan never blocks ingest and ingest never stalls a scan —
    the concurrency role of the reference's HBase client pool +
    region-parallel reads (hbase/StorageClient.scala:40,
    HBPEvents.scala:84-90).

    Source properties (``PIO_STORAGE_SOURCES_<NAME>_<KEY>``):

    - ``PATH``: database file (default ``<fs_basedir>/storage.db``)
    - ``SHARDS``: event-row shard count K (default 1). K > 1 opens K
      extra files ``<PATH>.shard<k>``, each an independent WAL write
      slot with its own group committer; single-event inserts hash to a
      shard by entity id (module docstring).
    - ``GROUP_COMMIT_EVENTS`` / ``GROUP_COMMIT_MS``: committer flush
      thresholds — rows per transaction (default 512) and max
      accumulation window in ms once a batch has ≥ 2 units (default 2).
    """

    def __init__(self, config=None):
        self.config = config
        props = getattr(config, "properties", {}) or {}
        path = props.get("PATH") or os.path.join(
            fs_basedir(),
            "storage.db",
        )
        if path != ":memory:":
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self.path = path
        self.conn = _open_wal_conn(path)
        self.lock = threading.RLock()
        self._init_dao_cache(self.lock)
        self.shard_count = self._pin_shard_count(
            max(1, int(props.get("SHARDS", 1) or 1))
        )
        gc_rows = int(props.get("GROUP_COMMIT_EVENTS", 512) or 512)
        gc_delay_s = float(props.get("GROUP_COMMIT_MS", 2.0) or 0.0) / 1e3
        # unit-atomicity granularity: batches up to this many rows per
        # shard commit as ONE unit; larger slices (bulk imports through
        # write()) split into chunks so no single unit can outgrow a
        # committer flush (see SQLiteLEvents.insert_batch)
        self.gc_rows = max(1, gc_rows)
        # the main file as a write slot (shares this conn + lock): the
        # K==1 write target, and always scanned as the legacy/residual
        # row store
        self.main_store = _ShardState(
            self.path, self.conn, self.lock, gc_rows, gc_delay_s
        )
        if self.shard_count <= 1:
            self.event_shards = [self.main_store]
        else:
            self.event_shards = [
                _ShardState.open(
                    ":memory:" if path == ":memory:"
                    else f"{path}.shard{k}",
                    gc_rows, gc_delay_s,
                )
                for k in range(self.shard_count)
            ]

    def _pin_shard_count(self, configured: int) -> int:
        """The shard count is part of the DATA layout (crc32 % K routes
        every entity), so it is pinned in the main file at first use and
        validated on every open: reopening a K-sharded database with a
        different K (or none) would silently hide the shard files' rows
        from every scan, or re-route entities away from their history.
        Changing K requires export + re-import. Read-only files (and
        pre-pin single-file databases) skip the pin and keep K=1
        semantics."""
        try:
            with self.lock:
                self.conn.execute(
                    "CREATE TABLE IF NOT EXISTS pio_shard_meta ("
                    "key TEXT PRIMARY KEY, value TEXT)"
                )
                # OR IGNORE: multi-process workers (SO_REUSEPORT) race
                # this first-open write; losers read the winner's pin
                self.conn.execute(
                    "INSERT OR IGNORE INTO pio_shard_meta VALUES "
                    "('shard_count', ?)",
                    (str(configured),),
                )
                self.conn.commit()
                row = self.conn.execute(
                    "SELECT value FROM pio_shard_meta WHERE key='shard_count'"
                ).fetchone()
        except sqlite3.OperationalError:
            # e.g. a read-only database file: honor the configuration
            # (reads of a sharded db still need the right K to fan out)
            return configured
        pinned = int(row[0])
        if pinned == configured:
            return pinned
        if pinned == 1:
            # 1 -> K is the safe upgrade: every existing row is in the
            # main file, which is always scanned first, and no entity
            # has shard-file history to be re-routed away from
            with self.lock:
                self.conn.execute(
                    "UPDATE pio_shard_meta SET value=? "
                    "WHERE key='shard_count'",
                    (str(configured),),
                )
                self.conn.commit()
            return configured
        raise StorageError(
            f"database {self.path!r} was sharded with SHARDS={pinned} "
            f"but is being opened with SHARDS={configured}; the shard "
            "count routes entities to files and cannot change in place "
            "once rows exist in shard files — reopen with "
            f"SHARDS={pinned}, or export and re-import to re-shard"
        )

    def close(self) -> None:
        """Stop every shard's committer (draining queued units) and
        close the shard + main connections. For embedders that own a
        Storage universe's lifecycle; the module-default client lives
        for the process."""
        for shard in self.event_shards:
            shard.committer.close()
        if self.main_store not in self.event_shards:
            self.main_store.committer.close()
        for shard in self.event_shards:
            if shard is not self.main_store:
                with shard.lock:
                    shard.conn.close()
        with self.lock:
            self.conn.close()

    def shard_index_for(self, entity_id) -> int:
        """Stable entity→shard hash (crc32, not ``hash()`` — per-process
        salting would scatter one entity across files between runs)."""
        if self.shard_count <= 1:
            return 0
        return zlib.crc32(str(entity_id).encode("utf-8")) % self.shard_count

    def shard_for(self, entity_id) -> _ShardState:
        return self.event_shards[self.shard_index_for(entity_id)]

    def row_stores(self) -> List[_ShardState]:
        """Every store holding event ROWS, scan order: the main file
        first (legacy/pre-sharding rows), then the hash shards."""
        if self.shard_count <= 1:
            return [self.main_store]
        return [self.main_store] + self.event_shards

    def execute(self, sql: str, params=()) -> _LockedCursor:
        return _LockedCursor(self, sql, params)

    def read_execute(self, sql: str, params=()):
        """Snapshot read against the MAIN file (see
        :meth:`_ShardState.read_execute`)."""
        return self.main_store.read_execute(sql, params)

    def commit(self) -> None:
        with self.lock:
            self.conn.commit()

def _table_name(namespace: str, suffix: str) -> str:
    ns = "".join(c if c.isalnum() else "_" for c in (namespace or "pio"))
    return f"{ns}_{suffix}"


class SQLiteLEvents(base.LEvents):
    def __init__(self, client: StorageClient, config=None, namespace: str = ""):
        self._c = client
        self._ns = namespace or "pio"
        self._pages_schema_ok: set = set()

    def _ensure_pages_schema(self, t: str) -> None:
        """Migrate page tables from older layouts (memoized per table):
        databases whose events table predates the page store get the
        _pages/_dict tables created here (init() never re-runs for an
        existing app), and page tables created before a column existed
        are ALTERed (additive-only)."""
        if t in self._pages_schema_ok:
            return
        with self._c.lock:
            if not self._exists(t):
                # app never init()ed — read paths must stay read-only and
                # must not plant orphan page tables (do not memoize: the
                # app may be init()ed later)
                return
            try:
                # IF NOT EXISTS both statements: a no-op on an up-to-date
                # database, and self-heals one where only part of the
                # page schema was ever committed
                self._create_page_tables(t)
                self._c.commit()
            except sqlite3.OperationalError:
                # e.g. a read-only database file: reads proceed
                # (page-path callers guard on table existence);
                # writes surface sqlite's own error at INSERT time
                return
            cols = {
                row[1]
                for row in self._c.execute(
                    f"PRAGMA table_info({t}_pages)"
                ).fetchall()
            }
            if "dead" not in cols:
                self._c.execute(f"ALTER TABLE {t}_pages ADD COLUMN dead BLOB")
                self._c.commit()
            self._pages_schema_ok.add(t)

    def _events_table(self, app_id: int, channel_id: Optional[int]) -> str:
        name = _table_name(self._ns, f"events_{int(app_id)}")
        if channel_id is not None:
            name += f"_{int(channel_id)}"
        return name

    @staticmethod
    def _create_row_table(store, t: str) -> None:
        """Event-row DDL, identical in the main file and every shard
        file. Caller holds the store's lock."""
        store.conn.execute(
            f"""CREATE TABLE IF NOT EXISTS {t} (
                id TEXT PRIMARY KEY,
                event TEXT NOT NULL,
                entity_type TEXT NOT NULL,
                entity_id TEXT NOT NULL,
                target_entity_type TEXT,
                target_entity_id TEXT,
                properties TEXT,
                event_time TEXT NOT NULL,
                event_time_ms INTEGER NOT NULL,
                tags TEXT,
                pr_id TEXT,
                creation_time TEXT NOT NULL
            )"""
        )
        store.conn.execute(
            f"CREATE INDEX IF NOT EXISTS {t}_time ON {t} (event_time_ms)"
        )
        store.conn.execute(
            f"CREATE INDEX IF NOT EXISTS {t}_entity ON {t} "
            f"(entity_type, entity_id, event_time_ms)"
        )

    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        t = self._events_table(app_id, channel_id)
        with self._c.lock:
            self._create_row_table(self._c.main_store, t)
            self._create_page_tables(t)
            self._c.commit()
        for shard in self._c.event_shards:
            if shard is self._c.main_store:
                continue
            with shard.lock:
                self._create_row_table(shard, t)
                shard.conn.commit()
        return True

    def _create_page_tables(self, t: str) -> None:
        """Columnar page store DDL (see data/storage/columnar.py): bulk
        imports land here as dictionary-encoded numpy blobs — the role of
        the reference's HBase regions feeding partitioned columnar scans
        (hbase/HBPEvents.scala:84-90). Single-event inserts keep using
        the row table; scans merge both. Caller holds the lock."""
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {t}_pages (
                page INTEGER PRIMARY KEY AUTOINCREMENT,
                event TEXT NOT NULL,
                entity_type TEXT NOT NULL,
                target_entity_type TEXT NOT NULL,
                prop TEXT NOT NULL,
                n INTEGER NOT NULL,
                min_ms INTEGER NOT NULL,
                max_ms INTEGER NOT NULL,
                entities BLOB NOT NULL,
                targets BLOB NOT NULL,
                vals BLOB NOT NULL,
                times BLOB NOT NULL,
                dead BLOB
            )"""
        )
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {t}_dict (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                name TEXT UNIQUE NOT NULL
            )"""
        )

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        t = self._events_table(app_id, channel_id)
        with self._c.lock:
            self._c.execute(f"DROP TABLE IF EXISTS {t}")
            self._c.execute(f"DROP TABLE IF EXISTS {t}_pages")
            self._c.execute(f"DROP TABLE IF EXISTS {t}_dict")
            self._c.commit()
            self._c.main_store.known_tables.discard(t)
        for shard in self._c.event_shards:
            if shard is self._c.main_store:
                continue
            with shard.lock:
                shard.conn.execute(f"DROP TABLE IF EXISTS {t}")
                shard.conn.commit()
                shard.known_tables.discard(t)
        return True

    def close(self) -> None:
        pass

    def _exists(self, table: str) -> bool:
        cur = self._c.execute(
            "SELECT name FROM sqlite_master WHERE type='table' AND name=?", (table,)
        )
        return cur.fetchone() is not None

    def _exists_memo(self, table: str) -> bool:
        """_exists with positive-result memoization for hot write paths:
        the per-event sqlite_master probe was a measurable share of REST
        ingest. Only positive results memoize (a table created later must
        be seen); remove() invalidates. A table dropped by ANOTHER
        process after memoization surfaces as StorageError from the
        statement itself rather than this probe."""
        return self._c.main_store.has_table(table)

    def _ensure_shard_table(self, shard: _ShardState, t: str) -> None:
        """Shard files are populated lazily: a database init()ed before
        sharding was enabled (or before this app existed) gets the row
        table created in the shard on first write to it. The MAIN file's
        table is the authority on whether the app is initialized — this
        is only reached after that check passed."""
        if shard is self._c.main_store or shard.has_table(t):
            return
        with shard.lock:
            self._create_row_table(shard, t)
            shard.conn.commit()
            shard.known_tables.add(t)

    _INSERT_SQL = "INSERT OR REPLACE INTO {t} VALUES (?,?,?,?,?,?,?,?,?,?,?,?)"

    @staticmethod
    def _event_row(event: Event, eid: str) -> tuple:
        return (
            eid,
            event.event,
            event.entity_type,
            event.entity_id,
            event.target_entity_type,
            event.target_entity_id,
            json.dumps(event.properties.to_json()),
            format_iso8601(event.event_time),
            _ms(event.event_time),
            json.dumps(list(event.tags)),
            event.pr_id,
            format_iso8601(event.creation_time),
        )

    def _scrub_duplicate_ids(self, t: str, spares) -> None:
        """INSERT OR REPLACE only replaces within ONE file — a client
        re-posting an EXPLICIT event id whose old row lives in another
        row store (pre-sharding main rows, or the same id re-posted with
        a different entity) would otherwise leave a stale duplicate that
        get() keeps returning. ``spares`` is ``[(event_id, keep_store)]``;
        each id is deleted from every OTHER row store in one batched
        transaction per store. Called AFTER the replacement row's commit:
        a failed insert then never loses the old row (the reverse order
        could drop the event entirely), at the price that a crash in the
        narrow window between commit and scrub leaves a duplicate of an
        explicitly re-posted id — duplicates over data loss. Explicit ids
        are the rare path (imports, updates); server-generated ids never
        pay this probe."""
        if not spares:
            return
        for store in self._c.row_stores():
            ids = [eid for eid, keep in spares if keep is not store]
            if not ids or not store.has_table(t):
                continue
            with store.lock:
                deleted = False
                for s in range(0, len(ids), 500):  # bound-param headroom
                    part = ids[s : s + 500]
                    cur = store.conn.execute(
                        f"DELETE FROM {t} WHERE id IN "
                        f"({','.join('?' * len(part))})",
                        part,
                    )
                    deleted = deleted or cur.rowcount > 0
                if deleted:
                    store.conn.commit()
                else:
                    store.conn.rollback()

    def insert(self, event: Event, app_id: int, channel_id: Optional[int] = None) -> str:
        """Single-event insert through the per-shard GROUP COMMITTER: the
        row is enqueued, the shard's committer coalesces it with whatever
        else is in flight into one transaction, and this call returns
        after that transaction's COMMIT — the returned id is durable (to
        the WAL) exactly as before, but N concurrent inserts now pay one
        commit, not N."""
        t = self._events_table(app_id, channel_id)
        eid = event.event_id or new_event_id()
        if not self._exists_memo(t):
            raise StorageError(f"events table {t} not initialized")
        shard = self._c.shard_for(event.entity_id)
        self._ensure_shard_table(shard, t)
        shard.submit_rows(
            self._INSERT_SQL.format(t=t), [self._event_row(event, eid)]
        ).wait()
        if event.event_id:
            self._scrub_duplicate_ids(t, [(eid, shard)])
        return eid

    def insert_batch(
        self,
        events: Sequence[Event],
        app_id: int,
        channel_id: Optional[int] = None,
    ) -> List[str]:
        """Batch insert (the ``/batch/events.json`` path): the batch is
        split by shard and each shard's slice rides the group committer
        as an atomic unit — a reader can never observe part of a unit.
        Slices larger than ``GROUP_COMMIT_EVENTS`` rows (bulk imports
        through ``write()``) split into chunked units of that size, so
        no unit can outgrow a committer flush; the <=50-event REST batch
        is always one unit per shard. With K > 1 a batch spanning shards
        is atomic PER SHARD, not globally — a failure after some shards
        committed raises :class:`PartialBatchError` naming exactly which
        event ids did NOT land, so the REST route reports per-event
        outcomes. Shard slices commit in parallel; this returns after
        every slice resolves."""
        events = list(events)
        if not events:
            return []
        t = self._events_table(app_id, channel_id)
        if not self._exists_memo(t):
            raise StorageError(f"events table {t} not initialized")
        eids = [e.event_id or new_event_id() for e in events]
        # duplicate EXPLICIT ids within one batch are last-wins, exactly
        # like single-file INSERT OR REPLACE: earlier occurrences never
        # reach a shard, so the post-commit scrub can't delete the
        # survivor from its own store
        last_slot: Dict[str, int] = {
            eid: j
            for j, (event, eid) in enumerate(zip(events, eids))
            if event.event_id
        }
        by_shard: Dict[int, list] = {}  # shard idx -> [(row, eid)]
        explicit: list = []  # (eid, keep_store) to scrub post-commit
        for j, (event, eid) in enumerate(zip(events, eids)):
            if event.event_id and last_slot[eid] != j:
                continue  # superseded later in this same batch
            k = self._c.shard_index_for(event.entity_id)
            if event.event_id:
                explicit.append((eid, self._c.event_shards[k]))
            by_shard.setdefault(k, []).append((self._event_row(event, eid), eid))
        sql = self._INSERT_SQL.format(t=t)
        chunk = self._c.gc_rows
        units: list = []  # (unit, [eids])
        for k, pairs in by_shard.items():
            shard = self._c.event_shards[k]
            self._ensure_shard_table(shard, t)
            for s in range(0, len(pairs), chunk):
                part = pairs[s : s + chunk]
                units.append(
                    (
                        shard.submit_rows(sql, [row for row, _ in part]),
                        [eid for _, eid in part],
                    )
                )
        failed: list = []
        first_error: Optional[BaseException] = None
        for unit, unit_eids in units:
            try:
                unit.wait()
            except BaseException as e:
                failed.extend(unit_eids)
                if first_error is None:
                    first_error = e
        # scrub explicit ids only where the REPLACEMENT actually landed
        # (a failed unit must keep the old copy — see _scrub_duplicate_ids)
        failed_set = set(failed)
        self._scrub_duplicate_ids(
            t, [(eid, keep) for eid, keep in explicit if eid not in failed_set]
        )
        if first_error is not None:
            if len(failed) == len(eids):
                raise first_error  # nothing landed: plain error
            raise PartialBatchError(
                f"{len(failed)}/{len(eids)} batch events failed to "
                f"commit: {first_error}",
                event_ids=eids,
                failed_ids=failed,
            ) from first_error
        return eids

    @staticmethod
    def _row_to_event(row) -> Event:
        return Event(
            event_id=row[0],
            event=row[1],
            entity_type=row[2],
            entity_id=row[3],
            target_entity_type=row[4],
            target_entity_id=row[5],
            properties=DataMap(json.loads(row[6]) if row[6] else {}),
            event_time=parse_iso8601(row[7]),
            tags=tuple(json.loads(row[9]) if row[9] else ()),
            pr_id=row[10],
            creation_time=parse_iso8601(row[11]),
        )

    @staticmethod
    def _parse_page_id(event_id: str):
        """Bulk-imported events carry synthetic ids ``pg-<page>-<idx>``."""
        if not event_id.startswith("pg-"):
            return None
        try:
            _, page, idx = event_id.split("-", 2)
            return int(page), int(idx)
        except ValueError:
            return None

    def _get_page_event(
        self, t: str, page: int, idx: int
    ) -> Optional[Event]:
        import numpy as np

        self._ensure_pages_schema(t)
        with self._c.lock:
            if not self._exists(f"{t}_pages"):
                return None
            row = self._c.execute(
                f"SELECT event, entity_type, target_entity_type, prop, n, "
                f"entities, targets, vals, times, dead "
                f"FROM {t}_pages WHERE page=?",
                (page,),
            ).fetchone()
        if row is None or idx >= row[4]:
            return None
        ev, et, tet, prop, n, eb, gb, vb, tb, db = row
        if db is not None and np.frombuffer(db, np.uint8)[idx]:
            return None  # tombstoned
        names = self._dict_names(t)
        when = _dt.datetime.fromtimestamp(
            int(np.frombuffer(tb, np.int64)[idx]) / 1000.0, _dt.timezone.utc
        )
        return Event(
            event_id=f"pg-{page}-{idx}",
            event=ev,
            entity_type=et,
            entity_id=names[np.frombuffer(eb, np.int32)[idx]],
            target_entity_type=tet,
            target_entity_id=names[np.frombuffer(gb, np.int32)[idx]],
            properties=DataMap(
                {prop: float(np.frombuffer(vb, np.float32)[idx])}
            ),
            event_time=when,
            creation_time=when,
        )

    def get(
        self, event_id: str, app_id: int, channel_id: Optional[int] = None
    ) -> Optional[Event]:
        t = self._events_table(app_id, channel_id)
        pg = self._parse_page_id(event_id)
        if pg is not None:
            return self._get_page_event(t, *pg)
        with self._c.lock:
            if not self._exists(t):
                raise StorageError(f"events table {t} not initialized")
        # event ids don't encode their shard (the entity hash needs the
        # entity id), so probe each row store; K is small and the id
        # column is the primary key
        for store in self._c.row_stores():
            if not store.has_table(t):
                continue
            row = store.execute(
                f"SELECT * FROM {t} WHERE id=?", (event_id,)
            ).fetchone()
            if row:
                return self._row_to_event(row)
        return None

    def _delete_page_event(self, t: str, page: int, idx: int) -> bool:
        """Delete one row of a page by marking its tombstone bit. The
        page is never compacted, so the positional event ids
        (``pg-<page>-<idx>``) of the surviving rows stay STABLE — a
        compaction would silently re-address later rows, making a second
        delete remove the wrong event. A fully-dead page is dropped."""
        import numpy as np

        self._ensure_pages_schema(t)
        with self._c.lock:
            if not self._exists(f"{t}_pages"):
                return False
            row = self._c.execute(
                f"SELECT n, dead FROM {t}_pages WHERE page=?", (page,)
            ).fetchone()
            if row is None or idx >= row[0]:
                return False
            n, dead_blob = row
            dead = (
                np.frombuffer(dead_blob, np.uint8).copy()
                if dead_blob is not None
                else np.zeros(n, np.uint8)
            )
            if dead[idx]:
                return False  # already deleted
            dead[idx] = 1
            if int(dead.sum()) == n:
                self._c.conn.execute(
                    f"DELETE FROM {t}_pages WHERE page=?", (page,)
                )
            else:
                self._c.conn.execute(
                    f"UPDATE {t}_pages SET dead=? WHERE page=?",
                    (dead.tobytes(), page),
                )
            self._c.conn.commit()
            return True

    def delete(
        self, event_id: str, app_id: int, channel_id: Optional[int] = None
    ) -> bool:
        t = self._events_table(app_id, channel_id)
        pg = self._parse_page_id(event_id)
        if pg is not None:
            return self._delete_page_event(t, *pg)
        with self._c.lock:
            if not self._exists(t):
                raise StorageError(f"events table {t} not initialized")
        # deletes are rare: a direct per-store transaction, not the
        # group committer (same shard probe rationale as get())
        for store in self._c.row_stores():
            if not store.has_table(t):
                continue
            with store.lock:
                cur = store.conn.execute(
                    f"DELETE FROM {t} WHERE id=?", (event_id,)
                )
                store.conn.commit()
            if cur.rowcount > 0:
                return True
        return False

    @staticmethod
    def _find_clauses(
        start_time, until_time, entity_type, entity_id, event_names,
        target_entity_type, target_entity_id,
    ):
        clauses: List[str] = []
        params: list = []
        if start_time is not None:
            clauses.append("event_time_ms >= ?")
            params.append(_ms(start_time))
        if until_time is not None:
            clauses.append("event_time_ms < ?")
            params.append(_ms(until_time))
        if entity_type is not None:
            clauses.append("entity_type = ?")
            params.append(entity_type)
        if entity_id is not None:
            clauses.append("entity_id = ?")
            params.append(entity_id)
        if event_names is not None:
            if event_names:
                clauses.append(
                    "event IN (" + ",".join("?" * len(event_names)) + ")"
                )
                params.extend(event_names)
            else:
                clauses.append("1=0")  # empty allow-list matches nothing
        if target_entity_type is not UNSET:
            if target_entity_type is None:
                clauses.append("target_entity_type IS NULL")
            else:
                clauses.append("target_entity_type = ?")
                params.append(target_entity_type)
        if target_entity_id is not UNSET:
            if target_entity_id is None:
                clauses.append("target_entity_id IS NULL")
            else:
                clauses.append("target_entity_id = ?")
                params.append(target_entity_id)
        return clauses, params

    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: OptFilter = UNSET,
        target_entity_id: OptFilter = UNSET,
        limit: Optional[int] = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        t = self._events_table(app_id, channel_id)
        clauses, params = self._find_clauses(
            start_time, until_time, entity_type, entity_id, event_names,
            target_entity_type, target_entity_id,
        )
        sql = f"SELECT * FROM {t}"
        if clauses:
            sql += " WHERE " + " AND ".join(clauses)
        sql += f" ORDER BY event_time_ms {'DESC' if reversed else 'ASC'}"
        if limit is not None and limit >= 0:
            sql += f" LIMIT {int(limit)}"  # per-store bound; re-cut below
        with self._c.lock:
            if not self._exists(t):
                raise StorageError(f"events table {t} not initialized")
        # the potentially-large scans run on snapshot connections, so
        # concurrent ingest proceeds while these fetches stream; sharded
        # stores fan out per shard and merge (stable sort: ties keep
        # main-store-then-shard, insertion order). An entity_id filter
        # pins the events to ONE shard (the insert hash), so the serving
        # find-by-entity path scans main + that shard, not all K.
        candidates = self._c.row_stores()
        if entity_id is not None and self._c.shard_count > 1:
            candidates = [
                self._c.main_store, self._c.shard_for(entity_id)
            ]
        stores = [s for s in candidates if s.has_table(t)]
        row_events = [
            self._row_to_event(r)
            for store in stores
            for r in store.read_execute(sql, params).fetchall()
        ]
        # merge bulk-imported page events (rare on this legacy path — the
        # training scan is find_columns_native; here pages decode into
        # Event objects so find() stays a complete view of the store)
        page_events = self._page_events(
            t, start_time, until_time, entity_type, entity_id, event_names,
            target_entity_type, target_entity_id,
        )
        if not page_events and len(stores) <= 1:
            return iter(row_events)
        merged = row_events + page_events
        merged.sort(key=lambda e: _ms(e.event_time), reverse=reversed)
        if limit is not None and limit >= 0:
            merged = merged[: int(limit)]
        return iter(merged)

    # --- columnar page store (see data/storage/columnar.py) ---

    _PAGE_ROWS = 1 << 20

    def _dict_encode(self, t: str, names) -> "np.ndarray":
        """Distinct id strings -> global dictionary codes (insert-if-new)."""
        import numpy as np

        strs = [str(n) for n in names]
        with self._c.lock:
            self._c.conn.executemany(
                f"INSERT OR IGNORE INTO {t}_dict (name) VALUES (?)",
                ((s,) for s in strs),
            )
            mapping: Dict[str, int] = {}
            chunk = 900  # sqlite bound-parameter limit headroom
            for s in range(0, len(strs), chunk):
                part = strs[s : s + chunk]
                rows = self._c.conn.execute(
                    f"SELECT name, id FROM {t}_dict WHERE name IN "
                    f"({','.join('?' * len(part))})",
                    part,
                ).fetchall()
                mapping.update(rows)
            self._c.conn.commit()
        return np.array([mapping[s] for s in strs], np.int32)

    def _dict_names(self, t: str) -> "np.ndarray":
        """Global dictionary as an id-indexed name array."""
        import numpy as np

        rows = self._c.read_execute(
            f"SELECT id, name FROM {t}_dict"
        ).fetchall()
        size = (max(r[0] for r in rows) + 1) if rows else 0
        arr = np.empty(size, object)
        for i, name in rows:
            arr[i] = name
        return arr

    def insert_columns(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        event: str,
        entity_type: str,
        target_entity_type: str,
        entity_ids,
        target_ids,
        values,
        value_property: str = "rating",
        event_time: Optional[_dt.datetime] = None,
        event_times_ms=None,
    ) -> int:
        from predictionio_tpu.data.storage.columnar import encode_strings

        e_names, e_codes = encode_strings(entity_ids)
        g_names, g_codes = encode_strings(target_ids)
        return self.insert_columns_encoded(
            app_id,
            channel_id,
            event=event,
            entity_type=entity_type,
            target_entity_type=target_entity_type,
            entity_names=e_names,
            entity_codes=e_codes,
            target_names=g_names,
            target_codes=g_codes,
            values=values,
            value_property=value_property,
            event_time=event_time,
            event_times_ms=event_times_ms,
        )

    def insert_columns_encoded(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        event: str,
        entity_type: str,
        target_entity_type: str,
        entity_names,
        entity_codes,
        target_names,
        target_codes,
        values,
        value_property: str = "rating",
        event_time: Optional[_dt.datetime] = None,
        event_times_ms=None,
    ) -> int:
        """Vectorized bulk append: dictionary-encode the (pre-factorized)
        id columns and store numpy blobs as pages — 20M events import in
        seconds where the row path takes minutes (the role of the
        reference's HBase bulk region writes). ``event_times_ms`` keeps
        per-row timestamps (import round-trips); otherwise every row gets
        ``event_time`` (default now)."""
        import numpy as np

        if event.startswith("$"):
            raise StorageError(
                f"insert_columns cannot write special event {event!r}"
            )
        t = self._events_table(app_id, channel_id)
        with self._c.lock:
            if not self._exists(t):
                raise StorageError(f"events table {t} not initialized")
        # pre-page-store databases lack the _pages/_dict tables entirely
        self._ensure_pages_schema(t)
        vals = np.asarray(values, np.float32)
        e_codes = np.asarray(entity_codes, np.int32)
        g_codes = np.asarray(target_codes, np.int32)
        n = len(vals)
        if n != len(e_codes) or n != len(g_codes):
            raise ValueError("entity/target/values column lengths differ")
        if n == 0:
            return 0
        e_glob = self._dict_encode(t, entity_names)[e_codes]
        g_glob = self._dict_encode(t, target_names)[g_codes]
        if event_times_ms is not None:
            times = np.asarray(event_times_ms, np.int64)
            if len(times) != n:
                raise ValueError("event_times_ms length differs")
        else:
            tms = _ms(event_time or _dt.datetime.now(_dt.timezone.utc))
            times = np.full(n, tms, np.int64)
        with self._c.lock:
            for s in range(0, n, self._PAGE_ROWS):
                e = slice(s, min(s + self._PAGE_ROWS, n))
                cnt = e.stop - e.start
                ts = times[e]
                self._c.conn.execute(
                    f"INSERT INTO {t}_pages (event, entity_type, "
                    "target_entity_type, prop, n, min_ms, max_ms, "
                    "entities, targets, vals, times) "
                    "VALUES (?,?,?,?,?,?,?,?,?,?,?)",
                    (
                        event, entity_type, target_entity_type,
                        value_property, cnt, int(ts.min()), int(ts.max()),
                        e_glob[e].tobytes(), g_glob[e].tobytes(),
                        vals[e].tobytes(), ts.tobytes(),
                    ),
                )
            self._c.conn.commit()
        return n

    @staticmethod
    def _page_filter(
        start_time, until_time, entity_type, event_names,
        target_entity_type,
    ):
        """Page-level WHERE ``(clauses, params)`` shared by every page
        scan (monolithic, streaming, legacy find view), or None when no
        page can match. Pages only hold target-carrying events, so an
        explicit target_entity_type IS NULL filter matches none."""
        if target_entity_type is None:  # explicit "no target" filter
            return None
        clauses, params = [], []
        if event_names is not None:
            if not event_names:
                return None
            clauses.append(
                "event IN (" + ",".join("?" * len(event_names)) + ")"
            )
            params.extend(event_names)
        if entity_type is not None:
            clauses.append("entity_type = ?")
            params.append(entity_type)
        if target_entity_type is not UNSET:
            clauses.append("target_entity_type = ?")
            params.append(target_entity_type)
        if start_time is not None:
            clauses.append("max_ms >= ?")
            params.append(_ms(start_time))
        if until_time is not None:
            clauses.append("min_ms < ?")
            params.append(_ms(until_time))
        return clauses, params

    def _page_rows(
        self, t, start_time, until_time, entity_type, event_names,
        target_entity_type,
    ):
        """Pages matching the coarse (page-level) filters."""
        filt = self._page_filter(
            start_time, until_time, entity_type, event_names,
            target_entity_type,
        )
        if filt is None:
            return []
        self._ensure_pages_schema(t)
        clauses, params = filt
        sql = (
            f"SELECT page, event, entity_type, target_entity_type, prop, "
            f"n, min_ms, max_ms, entities, targets, vals, times, dead "
            f"FROM {t}_pages"
        )
        if clauses:
            sql += " WHERE " + " AND ".join(clauses)
        with self._c.lock:
            if not self._exists(f"{t}_pages"):
                return []
        return self._c.read_execute(sql, params).fetchall()

    def _page_events(
        self, t, start_time, until_time, entity_type, entity_id,
        event_names, target_entity_type, target_entity_id,
    ) -> List[Event]:
        """Decode page rows into Event objects (legacy find() view)."""
        import numpy as np

        pages = self._page_rows(
            t, start_time, until_time, entity_type, event_names,
            target_entity_type,
        )
        if not pages or target_entity_id is None:
            return []

        def code_of(name: str):
            row = self._c.execute(
                f"SELECT id FROM {t}_dict WHERE name=?", (name,)
            ).fetchone()
            return row[0] if row else None

        # entity filters compare int32 dict CODES, not strings: a
        # serving-path find_by_entity over a 20M-row bulk import must
        # stay vectorized (object-array string equality would burn the
        # serving deadline)
        e_code = g_code = None
        if entity_id is not None:
            e_code = code_of(entity_id)
            if e_code is None:
                return []
        if target_entity_id is not UNSET:
            g_code = code_of(target_entity_id)
            if g_code is None:
                return []
        names = self._dict_names(t)
        out: List[Event] = []
        lo = _ms(start_time) if start_time is not None else None
        hi = _ms(until_time) if until_time is not None else None
        for (
            page, ev, et, tet, prop, n, min_ms, max_ms, eb, gb, vb, tb, db
        ) in pages:
            e = np.frombuffer(eb, np.int32)
            g = np.frombuffer(gb, np.int32)
            v = np.frombuffer(vb, np.float32)
            ts = np.frombuffer(tb, np.int64)
            keep = (
                np.frombuffer(db, np.uint8) == 0
                if db is not None
                else np.ones(n, bool)
            )
            if lo is not None:
                keep = keep & (ts >= lo)
            if hi is not None:
                keep = keep & (ts < hi)
            if e_code is not None:
                keep = keep & (e == e_code)
            if g_code is not None:
                keep = keep & (g == g_code)
            for j in np.nonzero(keep)[0]:
                when = _dt.datetime.fromtimestamp(
                    ts[j] / 1000.0, _dt.timezone.utc
                )
                out.append(
                    Event(
                        event_id=f"pg-{page}-{int(j)}",
                        event=ev,
                        entity_type=et,
                        entity_id=names[e[j]],
                        target_entity_type=tet,
                        target_entity_id=names[g[j]],
                        properties=DataMap({prop: float(v[j])}),
                        event_time=when,
                        creation_time=when,
                    )
                )
        return out

    def iter_row_events(
        self, app_id: int, channel_id: Optional[int] = None
    ) -> Iterator[Event]:
        """Row-store events ONLY (no page merge) — the export path pairs
        this with iter_export_pages so neither side is double-counted.
        Sharded stores merge every shard's rows back into one
        time-ordered view."""
        t = self._events_table(app_id, channel_id)
        with self._c.lock:
            if not self._exists(t):
                raise StorageError(f"events table {t} not initialized")
        sql = f"SELECT * FROM {t} ORDER BY event_time_ms ASC"
        stores = [s for s in self._c.row_stores() if s.has_table(t)]
        if len(stores) <= 1:
            rows = stores[0].read_execute(sql).fetchall() if stores else []
            return (self._row_to_event(r) for r in rows)
        events = [
            self._row_to_event(r)
            for store in stores
            for r in store.read_execute(sql).fetchall()
        ]
        events.sort(key=lambda e: _ms(e.event_time))
        return iter(events)

    def iter_export_pages(
        self, app_id: int, channel_id: Optional[int] = None
    ) -> Iterator[dict]:
        """Bulk-export view of the page store: one dict of decoded numpy
        columns per page (live rows only), for vectorized writers —
        exporting 20M events must not build 20M Event objects any more
        than importing them does. Keys: event, entity_type,
        target_entity_type, prop, event_ids, entity_ids, target_ids,
        values, times_ms."""
        import numpy as np

        t = self._events_table(app_id, channel_id)
        with self._c.lock:
            if not self._exists(t):
                raise StorageError(f"events table {t} not initialized")
        self._ensure_pages_schema(t)
        with self._c.lock:
            if not self._exists(f"{t}_pages"):
                return
        page_ids = [
            r[0]
            for r in self._c.read_execute(
                f"SELECT page FROM {t}_pages ORDER BY page"
            ).fetchall()
        ]
        if not page_ids:
            return
        names = self._dict_names(t)
        for page_id in page_ids:
            # one page's blobs at a time: peak memory stays one page, and
            # the snapshot connection never touches the writer lock
            row = self._c.read_execute(
                f"SELECT page, event, entity_type, target_entity_type, "
                f"prop, n, entities, targets, vals, times, dead "
                f"FROM {t}_pages WHERE page=?",
                (page_id,),
            ).fetchone()
            if row is None:
                continue  # deleted since listing
            (page, ev, et, tet, prop, n, eb, gb, vb, tb, db) = row
            alive = (
                np.nonzero(np.frombuffer(db, np.uint8) == 0)[0]
                if db is not None
                else np.arange(n)
            )
            if not len(alive):
                continue
            # positional ids stay stable across tombstones: the index in
            # the id is the ORIGINAL slot, not the live rank
            event_ids = np.char.add(
                f"pg-{page}-", alive.astype("U10")
            ).astype(object)
            yield {
                "event": ev,
                "entity_type": et,
                "target_entity_type": tet,
                "prop": prop,
                "event_ids": event_ids,
                "entity_ids": names[np.frombuffer(eb, np.int32)[alive]],
                "target_ids": names[np.frombuffer(gb, np.int32)[alive]],
                "values": np.frombuffer(vb, np.float32)[alive],
                "times_ms": np.frombuffer(tb, np.int64)[alive],
            }

    def find_columns_native(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        value_spec=None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        target_entity_type: OptFilter = UNSET,
        event_names: Optional[Sequence[str]] = None,
    ):
        """Binary columnar scan: np.frombuffer over the matching pages +
        a SQL-evaluated residual for row-store events — no per-event
        Python objects on the bulk path (reference
        JDBCPEvents.scala:51-129's partitioned scan)."""
        import numpy as np

        from predictionio_tpu.data.storage.columnar import (
            ColumnarEvents,
            ValueSpec,
        )

        spec = value_spec or ValueSpec()
        t = self._events_table(app_id, channel_id)
        with self._c.lock:
            if not self._exists(t):
                raise StorageError(f"events table {t} not initialized")
        parts: List[ColumnarEvents] = []

        pages = self._page_rows(
            t, start_time, until_time, entity_type, event_names,
            target_entity_type,
        )
        if pages:
            overrides = spec.overrides
            lo = _ms(start_time) if start_time is not None else None
            hi = _ms(until_time) if until_time is not None else None
            e_parts, g_parts, v_parts = [], [], []
            for (
                page, ev, et, tet, prop, n, min_ms, max_ms, eb, gb, vb, tb, db
            ) in pages:
                e = np.frombuffer(eb, np.int32)
                g = np.frombuffer(gb, np.int32)
                ov = overrides.get(ev)
                if ov is not None:
                    v = np.full(n, ov, np.float32)
                elif prop == spec.prop:
                    v = np.frombuffer(vb, np.float32)
                else:  # stored under a different property: all defaults
                    v = np.full(n, spec.default, np.float32)
                needs_time = (lo is not None and min_ms < lo) or (
                    hi is not None and max_ms >= hi
                )
                if needs_time or db is not None:
                    keep = (
                        np.frombuffer(db, np.uint8) == 0
                        if db is not None
                        else np.ones(n, bool)
                    )
                    if needs_time:
                        ts = np.frombuffer(tb, np.int64)
                        if lo is not None:
                            keep = keep & (ts >= lo)
                        if hi is not None:
                            keep = keep & (ts < hi)
                    e, g, v = e[keep], g[keep], v[keep]
                e_parts.append(e)
                g_parts.append(g)
                v_parts.append(v)
            e_all = np.concatenate(e_parts)
            g_all = np.concatenate(g_parts)
            v_all = np.concatenate(v_parts)
            if len(e_all):
                names = self._dict_names(t)

                def dense(codes):
                    # compress global dict codes to dense name-sorted
                    # indices via a presence bitmap + LUT — three linear
                    # passes instead of np.unique's 20M-element argsort
                    # (the whole scan's former hot spot)
                    seen = np.zeros(len(names), bool)
                    seen[codes] = True
                    present = np.nonzero(seen)[0]
                    pnames = names[present]
                    order = np.argsort(pnames)  # distinct-sized
                    lut = np.zeros(len(names), np.int32)
                    lut[present[order]] = np.arange(
                        len(present), dtype=np.int32
                    )
                    return pnames[order], lut[codes]

                ue_names, e_codes = dense(e_all)
                ug_names, g_codes = dense(g_all)
                parts.append(
                    ColumnarEvents(
                        entity_names=ue_names,
                        target_names=ug_names,
                        entity_codes=e_codes,
                        target_codes=g_codes,
                        values=v_all,
                    )
                )

        # residual row stores in deterministic order (main file, then
        # hash shards) — the SAME order the streaming scan yields them,
        # so both paths see one event sequence
        all_rows: list = []
        val_parts: list = []
        for store in self._c.row_stores():
            rows, values = self._residual_scan(
                store, t, spec, start_time, until_time, entity_type,
                target_entity_type, event_names,
            )
            if rows:
                all_rows.extend(rows)
                val_parts.append(values)
        if all_rows:
            from predictionio_tpu.data.storage.columnar import encode_strings

            e_names, e_codes = encode_strings([r[0] for r in all_rows])
            g_names, g_codes = encode_strings([r[1] for r in all_rows])
            parts.append(
                ColumnarEvents(
                    entity_names=e_names,
                    target_names=g_names,
                    entity_codes=e_codes,
                    target_codes=g_codes,
                    values=np.concatenate(val_parts),
                )
            )
        return ColumnarEvents.concat(parts)

    def _residual_scan(
        self, store, t, spec, start_time, until_time, entity_type,
        target_entity_type, event_names,
    ):
        """Row-store residual of a columnar scan (REST-posted tail) for
        ONE row store (the main file or a hash shard) — value evaluated
        IN SQL (CASE per event override + json_extract), so even this
        path never parses JSON in Python. Returns ``(rows, values)``:
        the raw (entity_id, target_entity_id, ...) rows and their
        float32 training values."""
        import numpy as np

        if not store.has_table(t):
            return [], None

        clauses, params = self._find_clauses(
            start_time, until_time, entity_type, None, event_names,
            target_entity_type, UNSET,
        )
        clauses.append("target_entity_id IS NOT NULL")
        case_sql = ""
        case_params: list = []
        null_case_sql = ""
        null_case_params: list = []
        for ev_name, const in spec.overrides.items():
            case_sql += "WHEN ? THEN ? "
            case_params.extend([ev_name, float(const)])
            # override events never read the property — mask their type
            # so junk values there stay permitted (value_of skips them)
            null_case_sql += "WHEN ? THEN NULL "
            null_case_params.append(ev_name)
        # json path via parameter; quoted so property names with dots
        # stay one key
        value_sql = (
            "CAST(COALESCE(json_extract(properties, ?), ?) AS REAL)"
        )
        type_sql = "json_type(properties, ?)"
        raw_sql = "json_extract(properties, ?)"
        if case_sql:
            value_sql = f"CASE event {case_sql}ELSE {value_sql} END"
            # mask BOTH helper columns for override events — their
            # properties are never read, so malformed JSON there must not
            # fail the scan (the value CASE short-circuits past it too)
            type_sql = f"CASE event {null_case_sql}ELSE {type_sql} END"
            raw_sql = f"CASE event {null_case_sql}ELSE {raw_sql} END"
        sql = (
            f"SELECT entity_id, target_entity_id, {value_sql}, "
            f"{type_sql}, {raw_sql} FROM {t} "
            "WHERE " + " AND ".join(clauses)
        )
        prop_path = '$."' + spec.prop.replace('"', '""') + '"'
        all_params = (
            case_params + [prop_path, float(spec.default)]
            + null_case_params + [prop_path]
            + null_case_params + [prop_path] + params
        )
        rows = store.read_execute(sql, all_params).fetchall()
        if not rows:
            return [], None
        # CAST diverges from the per-event path on non-numeric
        # property values (unparseable text silently becomes 0.0;
        # 'nan'/'inf' strings parse in Python but not in CAST) — for
        # the rare rows whose json_type is not numeric, apply the
        # same float() rule ValueSpec.value_of uses, so bad events
        # surface (raise) and parseable text agrees exactly.
        # json null / missing keep the COALESCE default, as value_of
        # keeps its default.
        values = np.fromiter(
            (
                r[2]
                if r[3] in (None, "null", "integer", "real", "true", "false")
                else float(r[4])
                for r in rows
            ),
            np.float32,
            count=len(rows),
        )
        return rows, values

    def stream_columns_native(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        *,
        value_spec=None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        target_entity_type: OptFilter = UNSET,
        event_names: Optional[Sequence[str]] = None,
        batch_rows: int = 1_048_576,
    ):
        """Chunked binary columnar scan: one batch per page (split past
        ``batch_rows``), all batches in the TABLE-GLOBAL dictionary code
        space, plus a final batch for the row-store residual whose new
        ids extend that space. The page-id list is snapshotted up front
        (ids only, no blobs), so peak memory is one page and a page
        inserted mid-scan is simply not part of this scan — exactly the
        WAL snapshot semantics of the monolithic scan."""
        import numpy as np

        from predictionio_tpu.data.storage.columnar import (
            ColumnarStream,
            ValueSpec,
        )

        spec = value_spec or ValueSpec()
        t = self._events_table(app_id, channel_id)
        with self._c.lock:
            if not self._exists(t):
                raise StorageError(f"events table {t} not initialized")
        # fingerprint BEFORE the scan: a concurrent write during the scan
        # then makes the next cache lookup miss, never hit stale
        fingerprint = self.store_fingerprint(app_id, channel_id)
        self._ensure_pages_schema(t)
        page_ids: List[int] = []
        # ids only, no blobs (peak memory stays one page); the filter is
        # the SAME clause builder the monolithic scan uses, so both paths
        # select identical pages by construction
        filt = self._page_filter(
            start_time, until_time, entity_type, event_names,
            target_entity_type,
        )
        if filt is not None:
            clauses, params = filt
            sql = f"SELECT page FROM {t}_pages"
            if clauses:
                sql += " WHERE " + " AND ".join(clauses)
            with self._c.lock:
                have_pages = self._exists(f"{t}_pages")
            if have_pages:
                page_ids = [
                    r[0]
                    for r in self._c.read_execute(
                        sql + " ORDER BY page", params
                    ).fetchall()
                ]
        names_state = {"names": self._dict_names(t), "extra": []}

        def batches():
            overrides = spec.overrides
            lo = _ms(start_time) if start_time is not None else None
            hi = _ms(until_time) if until_time is not None else None
            for page_id in page_ids:
                row = self._c.read_execute(
                    f"SELECT event, prop, n, min_ms, max_ms, entities, "
                    f"targets, vals, times, dead FROM {t}_pages "
                    f"WHERE page=?",
                    (page_id,),
                ).fetchone()
                if row is None:
                    continue  # deleted since listing
                ev, prop, n, min_ms, max_ms, eb, gb, vb, tb, db = row
                e = np.frombuffer(eb, np.int32)
                g = np.frombuffer(gb, np.int32)
                ov = overrides.get(ev)
                if ov is not None:
                    v = np.full(n, ov, np.float32)
                elif prop == spec.prop:
                    v = np.frombuffer(vb, np.float32)
                else:  # stored under a different property: all defaults
                    v = np.full(n, spec.default, np.float32)
                needs_time = (lo is not None and min_ms < lo) or (
                    hi is not None and max_ms >= hi
                )
                if needs_time or db is not None:
                    keep = (
                        np.frombuffer(db, np.uint8) == 0
                        if db is not None
                        else np.ones(n, bool)
                    )
                    if needs_time:
                        ts = np.frombuffer(tb, np.int64)
                        if lo is not None:
                            keep = keep & (ts >= lo)
                        if hi is not None:
                            keep = keep & (ts < hi)
                    e, g, v = e[keep], g[keep], v[keep]
                for s in range(0, len(v), batch_rows):
                    sl = slice(s, s + batch_rows)
                    if len(v[sl]):
                        yield e[sl], g[sl], v[sl]
            # residual row stores in deterministic order (main file,
            # then hash shards — the same order find_columns_native
            # concatenates them). All stores' ids map into ONE shared
            # code space through a name->code dict; unseen ids extend it
            # (the residual is the REST tail — small next to the page
            # bulk). Events of one entity live in one shard, so each
            # entity's events keep their per-store insertion order and
            # the consumer's stable counting-sort merge reproduces the
            # single-file wire byte-for-byte.
            code_of: Optional[dict] = None

            def enc(strs):
                out = np.empty(len(strs), np.int32)
                for j, s in enumerate(strs):
                    c = code_of.get(s)
                    if c is None:
                        c = len(code_of)
                        code_of[s] = c
                        names_state["extra"].append(s)
                    out[j] = c
                return out

            for store in self._c.row_stores():
                rows, values = self._residual_scan(
                    store, t, spec, start_time, until_time, entity_type,
                    target_entity_type, event_names,
                )
                if not rows:
                    continue
                if code_of is None:
                    code_of = {
                        str(nm): j
                        for j, nm in enumerate(names_state["names"])
                    }
                e_codes = enc([r[0] for r in rows])
                g_codes = enc([r[1] for r in rows])
                for s in range(0, len(values), batch_rows):
                    sl = slice(s, s + batch_rows)
                    if len(values[sl]):
                        yield e_codes[sl], g_codes[sl], values[sl]

        def names():
            base_names = names_state["names"]
            if not names_state["extra"]:
                return base_names
            extra = np.empty(len(names_state["extra"]), object)
            extra[:] = names_state["extra"]
            return np.concatenate([base_names, extra])

        return ColumnarStream(batches(), names, fingerprint=fingerprint)

    def store_fingerprint(
        self, app_id: int, channel_id: Optional[int] = None
    ) -> Optional[tuple]:
        """Cheap store-state aggregates: per row store (the main file
        plus every hash shard) a (count, max rowid, max event time)
        triple, + page store (count, max page id, total rows, max time)
        + exact tombstone populations. Every mutating path moves at
        least one component: inserts bump their shard's counts/max-rowid
        (INSERT OR REPLACE reassigns the implicit rowid), bulk imports
        add pages, deletes shrink counts or flip tombstone bits. Costs a
        few aggregate scans plus one pass over the (rare) dead blobs."""
        import numpy as np

        t = self._events_table(app_id, channel_id)
        with self._c.lock:
            if not self._exists(t):
                return None
        row = tuple(
            tuple(
                store.read_execute(
                    f"SELECT COUNT(*), COALESCE(MAX(rowid), 0), "
                    f"COALESCE(MAX(event_time_ms), 0) FROM {t}"
                ).fetchone()
            )
            if store.has_table(t)
            else (0, 0, 0)
            for store in self._c.row_stores()
        )
        pages = (0, 0, 0, 0)
        dead_sig: tuple = ()
        self._ensure_pages_schema(t)
        with self._c.lock:
            have_pages = self._exists(f"{t}_pages")
        if have_pages:
            pages = tuple(
                self._c.read_execute(
                    f"SELECT COUNT(*), COALESCE(MAX(page), 0), "
                    f"COALESCE(TOTAL(n), 0), COALESCE(MAX(max_ms), 0) "
                    f"FROM {t}_pages"
                ).fetchone()
            )
            dead_sig = tuple(
                (page, int(np.frombuffer(db, np.uint8).sum()))
                for page, db in self._c.read_execute(
                    f"SELECT page, dead FROM {t}_pages "
                    f"WHERE dead IS NOT NULL ORDER BY page"
                ).fetchall()
            )
        return ("sqlite", row, pages, dead_sig)


class _SQLiteMetaBase:
    def __init__(self, client: StorageClient, config=None, namespace: str = ""):
        self._c = client
        self._ns = namespace or "pio"
        with self._c.lock:
            self._create()
            self._c.commit()

    def _t(self, suffix: str) -> str:
        return _table_name(self._ns, suffix)

    def _create(self) -> None:
        raise NotImplementedError


class SQLiteApps(_SQLiteMetaBase, base.Apps):
    def _create(self):
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {self._t('apps')} (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                name TEXT NOT NULL UNIQUE,
                description TEXT)"""
        )

    def insert(self, app: App) -> Optional[int]:
        with self._c.lock:
            try:
                if app.id:
                    cur = self._c.execute(
                        f"INSERT INTO {self._t('apps')} (id,name,description) VALUES (?,?,?)",
                        (app.id, app.name, app.description),
                    )
                else:
                    cur = self._c.execute(
                        f"INSERT INTO {self._t('apps')} (name,description) VALUES (?,?)",
                        (app.name, app.description),
                    )
                self._c.commit()
                return cur.lastrowid if not app.id else app.id
            except sqlite3.IntegrityError:
                return None

    def get(self, app_id: int) -> Optional[App]:
        row = self._c.execute(
            f"SELECT id,name,description FROM {self._t('apps')} WHERE id=?", (app_id,)
        ).fetchone()
        return App(*row) if row else None

    def get_by_name(self, name: str) -> Optional[App]:
        row = self._c.execute(
            f"SELECT id,name,description FROM {self._t('apps')} WHERE name=?", (name,)
        ).fetchone()
        return App(*row) if row else None

    def get_all(self) -> List[App]:
        rows = self._c.execute(
            f"SELECT id,name,description FROM {self._t('apps')} ORDER BY id"
        ).fetchall()
        return [App(*r) for r in rows]

    def update(self, app: App) -> bool:
        with self._c.lock:
            cur = self._c.execute(
                f"UPDATE {self._t('apps')} SET name=?,description=? WHERE id=?",
                (app.name, app.description, app.id),
            )
            self._c.commit()
            return cur.rowcount > 0

    def delete(self, app_id: int) -> bool:
        with self._c.lock:
            cur = self._c.execute(
                f"DELETE FROM {self._t('apps')} WHERE id=?", (app_id,)
            )
            self._c.commit()
            return cur.rowcount > 0


class SQLiteAccessKeys(_SQLiteMetaBase, base.AccessKeys):
    def _create(self):
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {self._t('access_keys')} (
                key TEXT PRIMARY KEY, appid INTEGER NOT NULL, events TEXT)"""
        )

    def insert(self, access_key: AccessKey) -> Optional[str]:
        key = access_key.key or self.generate_key()
        with self._c.lock:
            try:
                self._c.execute(
                    f"INSERT INTO {self._t('access_keys')} VALUES (?,?,?)",
                    (key, access_key.appid, json.dumps(list(access_key.events))),
                )
                self._c.commit()
                return key
            except sqlite3.IntegrityError:
                return None

    @staticmethod
    def _row(row) -> AccessKey:
        return AccessKey(row[0], row[1], tuple(json.loads(row[2] or "[]")))

    def get(self, key: str) -> Optional[AccessKey]:
        row = self._c.execute(
            f"SELECT * FROM {self._t('access_keys')} WHERE key=?", (key,)
        ).fetchone()
        return self._row(row) if row else None

    def get_all(self) -> List[AccessKey]:
        return [
            self._row(r)
            for r in self._c.execute(
                f"SELECT * FROM {self._t('access_keys')}"
            ).fetchall()
        ]

    def get_by_app_id(self, app_id: int) -> List[AccessKey]:
        return [
            self._row(r)
            for r in self._c.execute(
                f"SELECT * FROM {self._t('access_keys')} WHERE appid=?", (app_id,)
            ).fetchall()
        ]

    def update(self, access_key: AccessKey) -> bool:
        with self._c.lock:
            cur = self._c.execute(
                f"UPDATE {self._t('access_keys')} SET appid=?,events=? WHERE key=?",
                (access_key.appid, json.dumps(list(access_key.events)), access_key.key),
            )
            self._c.commit()
            return cur.rowcount > 0

    def delete(self, key: str) -> bool:
        with self._c.lock:
            cur = self._c.execute(
                f"DELETE FROM {self._t('access_keys')} WHERE key=?", (key,)
            )
            self._c.commit()
            return cur.rowcount > 0


class SQLiteChannels(_SQLiteMetaBase, base.Channels):
    def _create(self):
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {self._t('channels')} (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                name TEXT NOT NULL, appid INTEGER NOT NULL)"""
        )

    def insert(self, channel: Channel) -> Optional[int]:
        if not Channel.is_valid_name(channel.name):
            return None
        with self._c.lock:
            if channel.id:
                self._c.execute(
                    f"INSERT INTO {self._t('channels')} (id,name,appid) VALUES (?,?,?)",
                    (channel.id, channel.name, channel.appid),
                )
                cid = channel.id
            else:
                cur = self._c.execute(
                    f"INSERT INTO {self._t('channels')} (name,appid) VALUES (?,?)",
                    (channel.name, channel.appid),
                )
                cid = cur.lastrowid
            self._c.commit()
            return cid

    def get(self, channel_id: int) -> Optional[Channel]:
        row = self._c.execute(
            f"SELECT id,name,appid FROM {self._t('channels')} WHERE id=?",
            (channel_id,),
        ).fetchone()
        return Channel(*row) if row else None

    def get_by_app_id(self, app_id: int) -> List[Channel]:
        rows = self._c.execute(
            f"SELECT id,name,appid FROM {self._t('channels')} WHERE appid=?",
            (app_id,),
        ).fetchall()
        return [Channel(*r) for r in rows]

    def delete(self, channel_id: int) -> bool:
        with self._c.lock:
            cur = self._c.execute(
                f"DELETE FROM {self._t('channels')} WHERE id=?", (channel_id,)
            )
            self._c.commit()
            return cur.rowcount > 0


class SQLiteEngineManifests(_SQLiteMetaBase, base.EngineManifests):
    def _create(self):
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {self._t('engine_manifests')} (
                id TEXT, version TEXT, name TEXT, description TEXT,
                files TEXT, engine_factory TEXT,
                PRIMARY KEY (id, version))"""
        )

    def insert(self, manifest: EngineManifest) -> None:
        self.update(manifest, upsert=True)

    def get(self, id: str, version: str) -> Optional[EngineManifest]:
        row = self._c.execute(
            f"SELECT * FROM {self._t('engine_manifests')} WHERE id=? AND version=?",
            (id, version),
        ).fetchone()
        if not row:
            return None
        return EngineManifest(
            row[0], row[1], row[2], row[3], tuple(json.loads(row[4] or "[]")), row[5]
        )

    def get_all(self) -> List[EngineManifest]:
        rows = self._c.execute(
            f"SELECT * FROM {self._t('engine_manifests')}"
        ).fetchall()
        return [
            EngineManifest(r[0], r[1], r[2], r[3], tuple(json.loads(r[4] or "[]")), r[5])
            for r in rows
        ]

    def update(self, manifest: EngineManifest, upsert: bool = False) -> None:
        with self._c.lock:
            self._c.execute(
                f"INSERT OR REPLACE INTO {self._t('engine_manifests')} VALUES (?,?,?,?,?,?)",
                (
                    manifest.id,
                    manifest.version,
                    manifest.name,
                    manifest.description,
                    json.dumps(list(manifest.files)),
                    manifest.engine_factory,
                ),
            )
            self._c.commit()

    def delete(self, id: str, version: str) -> None:
        with self._c.lock:
            self._c.execute(
                f"DELETE FROM {self._t('engine_manifests')} WHERE id=? AND version=?",
                (id, version),
            )
            self._c.commit()


_EI_COLS = (
    "id, status, start_time, end_time, engine_id, engine_version, "
    "engine_variant, engine_factory, batch, env, spark_conf, "
    "data_source_params, preparator_params, algorithms_params, serving_params"
)


class SQLiteEngineInstances(_SQLiteMetaBase, base.EngineInstances):
    def _create(self):
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {self._t('engine_instances')} (
                id TEXT PRIMARY KEY, status TEXT, start_time TEXT, end_time TEXT,
                engine_id TEXT, engine_version TEXT, engine_variant TEXT,
                engine_factory TEXT, batch TEXT, env TEXT, spark_conf TEXT,
                data_source_params TEXT, preparator_params TEXT,
                algorithms_params TEXT, serving_params TEXT)"""
        )

    @staticmethod
    def _row(r) -> EngineInstance:
        return EngineInstance(
            id=r[0],
            status=r[1],
            start_time=parse_iso8601(r[2]),
            end_time=parse_iso8601(r[3]),
            engine_id=r[4],
            engine_version=r[5],
            engine_variant=r[6],
            engine_factory=r[7],
            batch=r[8] or "",
            env=json.loads(r[9] or "{}"),
            spark_conf=json.loads(r[10] or "{}"),
            data_source_params=r[11] or "",
            preparator_params=r[12] or "",
            algorithms_params=r[13] or "",
            serving_params=r[14] or "",
        )

    def _write(self, i: EngineInstance) -> None:
        self._c.execute(
            f"INSERT OR REPLACE INTO {self._t('engine_instances')} "
            f"VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
            (
                i.id,
                i.status,
                _utc_iso(i.start_time),
                _utc_iso(i.end_time),
                i.engine_id,
                i.engine_version,
                i.engine_variant,
                i.engine_factory,
                i.batch,
                json.dumps(i.env),
                json.dumps(i.spark_conf),
                i.data_source_params,
                i.preparator_params,
                i.algorithms_params,
                i.serving_params,
            ),
        )

    def insert(self, instance: EngineInstance) -> str:
        import uuid

        iid = instance.id or uuid.uuid4().hex[:17]
        with self._c.lock:
            self._write(dataclasses.replace(instance, id=iid))
            self._c.commit()
        return iid

    def get(self, id: str) -> Optional[EngineInstance]:
        row = self._c.execute(
            f"SELECT {_EI_COLS} FROM {self._t('engine_instances')} WHERE id=?", (id,)
        ).fetchone()
        return self._row(row) if row else None

    def get_all(self) -> List[EngineInstance]:
        rows = self._c.execute(
            f"SELECT {_EI_COLS} FROM {self._t('engine_instances')}"
        ).fetchall()
        return [self._row(r) for r in rows]

    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> List[EngineInstance]:
        rows = self._c.execute(
            f"SELECT {_EI_COLS} FROM {self._t('engine_instances')} "
            "WHERE status=? AND engine_id=? AND engine_version=? AND engine_variant=? "
            "ORDER BY start_time DESC",
            (base.STATUS_COMPLETED, engine_id, engine_version, engine_variant),
        ).fetchall()
        return [self._row(r) for r in rows]

    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> Optional[EngineInstance]:
        out = self.get_completed(engine_id, engine_version, engine_variant)
        return out[0] if out else None

    def update(self, instance: EngineInstance) -> None:
        with self._c.lock:
            self._write(instance)
            self._c.commit()

    def delete(self, id: str) -> None:
        with self._c.lock:
            self._c.execute(
                f"DELETE FROM {self._t('engine_instances')} WHERE id=?", (id,)
            )
            self._c.commit()


class SQLiteEvaluationInstances(_SQLiteMetaBase, base.EvaluationInstances):
    def _create(self):
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {self._t('evaluation_instances')} (
                id TEXT PRIMARY KEY, status TEXT, start_time TEXT, end_time TEXT,
                evaluation_class TEXT, engine_params_generator_class TEXT,
                batch TEXT, env TEXT, spark_conf TEXT,
                evaluator_results TEXT, evaluator_results_html TEXT,
                evaluator_results_json TEXT)"""
        )

    @staticmethod
    def _row(r) -> EvaluationInstance:
        return EvaluationInstance(
            id=r[0],
            status=r[1],
            start_time=parse_iso8601(r[2]),
            end_time=parse_iso8601(r[3]),
            evaluation_class=r[4] or "",
            engine_params_generator_class=r[5] or "",
            batch=r[6] or "",
            env=json.loads(r[7] or "{}"),
            spark_conf=json.loads(r[8] or "{}"),
            evaluator_results=r[9] or "",
            evaluator_results_html=r[10] or "",
            evaluator_results_json=r[11] or "",
        )

    def _write(self, i: EvaluationInstance) -> None:
        self._c.execute(
            f"INSERT OR REPLACE INTO {self._t('evaluation_instances')} "
            f"VALUES (?,?,?,?,?,?,?,?,?,?,?,?)",
            (
                i.id,
                i.status,
                _utc_iso(i.start_time),
                _utc_iso(i.end_time),
                i.evaluation_class,
                i.engine_params_generator_class,
                i.batch,
                json.dumps(i.env),
                json.dumps(i.spark_conf),
                i.evaluator_results,
                i.evaluator_results_html,
                i.evaluator_results_json,
            ),
        )

    def insert(self, instance: EvaluationInstance) -> str:
        import uuid

        iid = instance.id or uuid.uuid4().hex[:17]
        with self._c.lock:
            self._write(dataclasses.replace(instance, id=iid))
            self._c.commit()
        return iid

    def get(self, id: str) -> Optional[EvaluationInstance]:
        row = self._c.execute(
            f"SELECT * FROM {self._t('evaluation_instances')} WHERE id=?", (id,)
        ).fetchone()
        return self._row(row) if row else None

    def get_all(self) -> List[EvaluationInstance]:
        rows = self._c.execute(
            f"SELECT * FROM {self._t('evaluation_instances')}"
        ).fetchall()
        return [self._row(r) for r in rows]

    def get_completed(self) -> List[EvaluationInstance]:
        rows = self._c.execute(
            f"SELECT * FROM {self._t('evaluation_instances')} "
            "WHERE status=? ORDER BY start_time DESC",
            (base.STATUS_COMPLETED,),
        ).fetchall()
        return [self._row(r) for r in rows]

    def update(self, instance: EvaluationInstance) -> None:
        with self._c.lock:
            self._write(instance)
            self._c.commit()

    def delete(self, id: str) -> None:
        with self._c.lock:
            self._c.execute(
                f"DELETE FROM {self._t('evaluation_instances')} WHERE id=?", (id,)
            )
            self._c.commit()


class SQLiteModels(_SQLiteMetaBase, base.Models):
    def _create(self):
        self._c.execute(
            f"""CREATE TABLE IF NOT EXISTS {self._t('models')} (
                id TEXT PRIMARY KEY, models BLOB)"""
        )

    def insert(self, model: Model) -> None:
        with self._c.lock:
            self._c.execute(
                f"INSERT OR REPLACE INTO {self._t('models')} VALUES (?,?)",
                (model.id, model.models),
            )
            self._c.commit()

    def get(self, id: str) -> Optional[Model]:
        row = self._c.execute(
            f"SELECT id, models FROM {self._t('models')} WHERE id=?", (id,)
        ).fetchone()
        return Model(row[0], row[1]) if row else None

    def delete(self, id: str) -> None:
        with self._c.lock:
            self._c.execute(
                f"DELETE FROM {self._t('models')} WHERE id=?", (id,)
            )
            self._c.commit()
